"""Mixture-of-experts layer + expert parallelism tests.

MoE is an extension beyond the reference (SURVEY §2.1: "EP ❌"); these
validate the routed MLP math (capacity, top-k combine, aux loss), parity of
the ep-sharded run with the unsharded one, and end-to-end training.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from megatron_llm_tpu.config import (
    ModelConfig,
    OptimizerConfig,
    ParallelConfig,
    RuntimeConfig,
    TrainConfig,
    tiny_config,
)
from megatron_llm_tpu.models import model as model_lib
from megatron_llm_tpu.models import moe as moe_lib
from megatron_llm_tpu.models import sharding as shard_lib
from megatron_llm_tpu.parallel import mesh as mesh_lib


def moe_cfg(**overrides):
    base = dict(
        vocab_size=64, hidden_size=32, num_layers=2, num_attention_heads=4,
        num_kv_heads=4, ffn_hidden_size=64, max_position_embeddings=64,
        seq_length=32, params_dtype="float32", attention_impl="dot",
        recompute="none", make_vocab_size_divisible_by=8,
        num_experts=4, moe_top_k=2,
    )
    base.update(overrides)
    return ModelConfig(**base).validate()


def test_moe_block_shapes_and_aux():
    cfg = moe_cfg()
    p = moe_lib.init_moe_params(jax.random.key(0), cfg)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 32, 32)),
                    jnp.float32)
    out, stats = moe_lib.moe_block(cfg, p, x)
    assert out.shape == x.shape
    aux = moe_lib.aux_loss_of(stats)
    assert np.isfinite(float(aux))
    # aux ≥ 1 (it is E·Σf·p with Σf = Σp = 1; minimum at uniform balance)
    assert float(aux) >= 0.99
    # observability stats: dropped fraction in [0,1], loads sum to 1
    assert 0.0 <= float(stats["dropped"]) <= 1.0
    np.testing.assert_allclose(float(jnp.sum(stats["load"])), 1.0,
                               rtol=1e-6)


def test_moe_top1_selects_single_expert():
    """With top_k=1 and ample capacity every token's output must equal the
    chosen expert's MLP applied to it, scaled by the router prob (Switch
    keeps the un-renormalized top-1 gate)."""
    cfg = moe_cfg(moe_top_k=1, moe_capacity_factor=8.0)
    p = moe_lib.init_moe_params(jax.random.key(1), cfg)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(1, 8, 32)),
                    jnp.float32)
    out, _ = moe_lib.moe_block(cfg, p, x)

    logits = np.asarray(x.astype(jnp.float32) @ p["router"])
    probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
    choice = logits.argmax(-1)[0]  # [s]
    from megatron_llm_tpu.ops.activations import get_activation

    act = get_activation(cfg.activation)
    for t in range(8):
        e = int(choice[t])
        xt = x[0, t]
        gate = xt @ p["w_gate"][e]
        up = xt @ p["w_up"][e]
        hidden = act(jnp.concatenate([gate, up]))
        want = probs[0, t, e] * (hidden @ p["w_down"][e])
        np.testing.assert_allclose(np.asarray(out[0, t]), np.asarray(want),
                                   atol=1e-5, rtol=1e-5)


def test_moe_capacity_drops_overflow():
    """Capacity masking: with a uniform router every token picks expert 0
    (argmax tie → lowest index) and only the first C tokens per group get
    dispatched — all later positions must come out exactly zero."""
    cfg = moe_cfg(moe_top_k=1, moe_capacity_factor=0.1)
    C = moe_lib.capacity(cfg, 32)
    assert C == 1
    p = moe_lib.init_moe_params(jax.random.key(2), cfg)
    p["router"] = jnp.zeros_like(p["router"])  # uniform probs → all pick e0
    x = jnp.asarray(np.random.default_rng(2).normal(size=(1, 32, 32)),
                    jnp.float32)
    out, stats = moe_lib.moe_block(cfg, p, x)
    out = np.asarray(out)
    assert np.abs(out[0, 0]).sum() > 0  # first token served by expert 0
    np.testing.assert_array_equal(out[0, 1:], 0.0)  # overflow dropped
    assert np.isfinite(float(moe_lib.aux_loss_of(stats)))
    # 31 of 32 assignments overflow the C=1 capacity
    np.testing.assert_allclose(float(stats["dropped"]), 31 / 32, rtol=1e-6)


def test_moe_model_forward_and_grad():
    cfg = moe_cfg()
    params = model_lib.init_params(jax.random.key(0), cfg)
    tokens = jnp.asarray(
        np.random.default_rng(3).integers(0, 64, (2, 32)), jnp.int32)
    logits, aux = model_lib.forward(cfg, params, tokens, return_aux=True)
    assert logits.shape == (2, 32, cfg.padded_vocab_size())
    assert np.isfinite(np.asarray(logits)).all()

    def loss(p):
        lg, a = model_lib.forward(cfg, p, tokens, return_aux=True)
        return jnp.mean(lg ** 2) + 0.01 * moe_lib.aux_loss_of(a)

    grads = jax.grad(loss)(params)
    gn = sum(float(jnp.sum(jnp.abs(g))) for g in jax.tree.leaves(grads))
    assert np.isfinite(gn) and gn > 0
    # router gets gradient through both the combine weights and the aux loss
    assert float(jnp.sum(jnp.abs(grads["layers"]["mlp"]["router"]))) > 0


def test_moe_ep_sharded_matches_unsharded(devices):
    cfg = moe_cfg()
    params = model_lib.init_params(jax.random.key(0), cfg)
    tokens = jnp.asarray(
        np.random.default_rng(4).integers(0, 64, (2, 32)), jnp.int32)
    want = model_lib.forward(cfg, params, tokens)

    devs = np.asarray(devices).reshape(2, 1, 1, 1, 4, 1, 1)  # dp2 × ep4
    mesh = Mesh(devs, mesh_lib.AXIS_ORDER)
    parallel = ParallelConfig(data_parallel=2, expert_parallel=4)
    specs = shard_lib.param_specs(cfg, parallel)
    sharded = shard_lib.shard_params(params, specs, mesh)
    tok = jax.device_put(tokens, NamedSharding(mesh, P("dp", None)))
    with mesh_lib.use_mesh(mesh):
        got = jax.jit(lambda p, t: model_lib.forward(cfg, p, t))(sharded, tok)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_moe_train_step_ep():
    """End-to-end: MoE model trains under dp×ep with ZeRO-1; loss finite and
    equal to the unsharded MoE loss."""
    from megatron_llm_tpu.training.driver import setup_train_state

    gen = np.random.default_rng(5)
    tokens = gen.integers(0, 64, (1, 4, 32))
    batch = {
        "tokens": jnp.asarray(tokens, jnp.int32),
        "labels": jnp.asarray(np.roll(tokens, -1, -1), jnp.int32),
        "loss_mask": jnp.ones((1, 4, 32), jnp.float32),
    }

    def run(ep):
        cfg = RuntimeConfig(
            model=tiny_config(num_experts=4, moe_top_k=2),
            parallel=ParallelConfig(data_parallel=2, expert_parallel=ep,
                                    use_distributed_optimizer=True),
            optimizer=OptimizerConfig(lr=1e-3, clip_grad=1.0),
            train=TrainConfig(train_iters=2, micro_batch_size=2,
                              global_batch_size=4, seq_length=32, save=None),
        ).validate()
        params = model_lib.init_params(jax.random.key(0), cfg.model)
        art = setup_train_state(cfg, params=params)
        _, metrics = art.step_fn(art.state, batch, None)
        return float(metrics["loss"]), metrics

    loss_ep, metrics = run(4)
    loss_ref, _ = run(1)
    assert np.isfinite(loss_ep)
    np.testing.assert_allclose(loss_ep, loss_ref, rtol=1e-4, atol=1e-4)
    # routing observability surfaces in the train metrics
    assert 0.0 <= float(metrics["moe_dropped_frac"]) <= 1.0
    assert float(metrics["moe_load_imbalance"]) >= 0.99
    assert np.isfinite(float(metrics["moe_aux_loss"]))


def test_dispatch_memory_scaling():
    """The grouped dispatch tensors must be E-independent (E·C is constant
    at fixed group size): XLA temp bytes equal at E=4 vs E=16 — the
    documented E-scaling property (models/moe.py docstring)."""
    def temp_bytes(E):
        cfg = moe_cfg(num_experts=E, hidden_size=64, ffn_hidden_size=128,
                      seq_length=256, max_position_embeddings=256)
        p = moe_lib.init_moe_params(jax.random.key(0), cfg)
        x = jnp.zeros((2, 256, 64), jnp.float32)
        c = jax.jit(
            lambda p, x: moe_lib.moe_block(cfg, p, x)).lower(p, x).compile()
        return c.memory_analysis().temp_size_in_bytes

    b4, b16 = temp_bytes(4), temp_bytes(16)
    assert abs(b16 - b4) / b4 < 0.1, (b4, b16)


def test_moe_through_pipeline():
    """MoE stats/aux tree flows through the pipelined schedule (pp=2)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from megatron_llm_tpu.parallel import pipeline as pipe

    cfg = tiny_config(num_layers=4, num_experts=4, moe_top_k=2,
                      params_dtype="float32", recompute="none",
                      seq_length=32, max_position_embeddings=32)
    parallel = ParallelConfig(pipeline_parallel=2, num_microbatches=3)
    runtime = RuntimeConfig(model=cfg, parallel=parallel,
                            optimizer=OptimizerConfig(),
                            train=TrainConfig(seq_length=32)).validate()
    mesh = mesh_lib.build_mesh(parallel)
    params = model_lib.init_params(jax.random.key(0), cfg)
    p_params = pipe.to_pipeline_params(params, parallel)
    specs = pipe.pipeline_param_specs(
        shard_lib.param_specs(cfg, parallel), parallel)
    p_params = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        p_params, specs, is_leaf=lambda v: isinstance(v, P))
    g = np.random.default_rng(0)
    batch = {
        "tokens": jnp.asarray(
            g.integers(0, cfg.vocab_size, (3, 2, 32)), jnp.int32),
        "labels": jnp.asarray(
            g.integers(0, cfg.vocab_size, (3, 2, 32)), jnp.int32),
        "loss_mask": jnp.ones((3, 2, 32), jnp.float32),
    }
    with mesh_lib.use_mesh(mesh):
        loss = jax.jit(
            lambda p, b: pipe.pipeline_loss(runtime, p, b, mesh=mesh)
        )(p_params, batch)
        grads = jax.jit(jax.grad(
            lambda p: pipe.pipeline_loss(runtime, p, batch, mesh=mesh)
        ))(p_params)
    assert np.isfinite(float(loss))
    assert all(np.isfinite(np.asarray(l)).all()
               for l in jax.tree.leaves(grads))
