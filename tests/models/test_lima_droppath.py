"""LIMA layer-dependent dropout + DropPath stochastic depth.

Reference: megatron/model/transformer.py:43-64 (DropPath) and :962-971
(linspace per-layer rate ramps).
"""

import jax
import jax.numpy as jnp
import numpy as np

from megatron_llm_tpu.config import tiny_config
from megatron_llm_tpu.models import model as model_lib
from megatron_llm_tpu.models.transformer import (
    _drop_path,
    _layer_rates,
    rope_tables,
)


def _cfg(**kw):
    return tiny_config(params_dtype="float32", recompute="none",
                       seq_length=16, max_position_embeddings=16, **kw)


def test_layer_rate_ramp_matches_linspace():
    cfg = _cfg(num_layers=4, hidden_dropout=0.4, lima_dropout=True,
               drop_path_rate=0.2)
    hs, dps = zip(*[_layer_rates(cfg, i) for i in range(4)])
    np.testing.assert_allclose(hs, np.linspace(0.0, 0.4, 4), rtol=1e-6)
    np.testing.assert_allclose(dps, np.linspace(0.0, 0.2, 4), rtol=1e-6)


def test_lima_first_layer_gets_zero_dropout():
    """With one layer, the LIMA ramp is [0.0] (linspace(0, p, 1)): the
    non-deterministic forward must equal the deterministic one even at a
    high nominal dropout rate — layer-0 truly gets rate 0."""
    cfg = _cfg(num_layers=1, hidden_dropout=0.9, lima_dropout=True)
    params = model_lib.init_params(jax.random.key(0), cfg)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 16)),
        jnp.int32)
    det = model_lib.forward(cfg, params, tokens)
    # embedding dropout also runs off hidden_dropout — zero it by comparing
    # through the stack only (embed rng split still happens)
    stoch = model_lib.forward(cfg, params, tokens,
                              rng=jax.random.key(7), deterministic=False)
    # embedding dropout is NOT LIMA-ramped (reference ramps layer dropout
    # only), so the outputs differ there; check the *stack* path instead
    from megatron_llm_tpu.models.transformer import (
        AttnSideInputs, stack_forward)

    cos, sin = rope_tables(cfg)
    x = model_lib.embed(cfg, {"embedding": params["embedding"]}, tokens)
    side = AttnSideInputs(rope_cos=cos, rope_sin=sin, deterministic=False)
    out_stoch, _ = stack_forward(cfg, params["layers"], x, side,
                                 jax.random.key(3))
    side_det = AttnSideInputs(rope_cos=cos, rope_sin=sin,
                              deterministic=True)
    out_det, _ = stack_forward(cfg, params["layers"], x, side_det, None)
    np.testing.assert_allclose(np.asarray(out_stoch), np.asarray(out_det),
                               rtol=1e-6, atol=1e-6)
    del det, stoch


def test_lima_off_keeps_flat_dropout():
    """Same single-layer setup without LIMA: dropout must actually fire."""
    cfg = _cfg(num_layers=1, hidden_dropout=0.9, lima_dropout=False)
    params = model_lib.init_params(jax.random.key(0), cfg)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 16)),
        jnp.int32)
    from megatron_llm_tpu.models.transformer import (
        AttnSideInputs, stack_forward)

    cos, sin = rope_tables(cfg)
    x = model_lib.embed(cfg, {"embedding": params["embedding"]}, tokens)
    side = AttnSideInputs(rope_cos=cos, rope_sin=sin, deterministic=False)
    out_stoch, _ = stack_forward(cfg, params["layers"], x, side,
                                 jax.random.key(3))
    side_det = AttnSideInputs(rope_cos=cos, rope_sin=sin,
                              deterministic=True)
    out_det, _ = stack_forward(cfg, params["layers"], x, side_det, None)
    assert not np.allclose(np.asarray(out_stoch), np.asarray(out_det),
                           rtol=1e-3)


def test_drop_path_per_sample_semantics():
    """DropPath zeroes whole samples of the branch and rescales the rest
    by 1/keep — reference transformer.py:52-64."""
    x = jnp.ones((512, 3, 4), jnp.float32)
    out = np.asarray(_drop_path(x, 0.5, jax.random.key(0),
                                deterministic=False))
    # each sample is either all-zero or all-2.0
    per_sample = out.reshape(512, -1)
    is_zero = np.all(per_sample == 0.0, axis=1)
    is_scaled = np.all(np.isclose(per_sample, 2.0), axis=1)
    assert np.all(is_zero | is_scaled)
    frac = is_zero.mean()
    assert 0.35 < frac < 0.65, frac  # ~Bernoulli(0.5)


def test_droppath_training_smoke_grads_finite():
    """Grads flow through lima+drop_path training (scan + remat path)."""
    from megatron_llm_tpu.config import (
        OptimizerConfig, ParallelConfig, RuntimeConfig, TrainConfig)
    from megatron_llm_tpu.training.step import compute_loss

    cfg = tiny_config(params_dtype="float32", recompute="selective",
                      seq_length=16, max_position_embeddings=16,
                      num_layers=4, hidden_dropout=0.2, lima_dropout=True,
                      drop_path_rate=0.3)
    runtime = RuntimeConfig(model=cfg, parallel=ParallelConfig(),
                            optimizer=OptimizerConfig(),
                            train=TrainConfig(seq_length=cfg.seq_length))
    params = model_lib.init_params(jax.random.key(0), cfg)
    g = np.random.default_rng(5)
    batch = {
        "tokens": jnp.asarray(g.integers(0, cfg.vocab_size, (2, 16)),
                              jnp.int32),
        "labels": jnp.asarray(g.integers(0, cfg.vocab_size, (2, 16)),
                              jnp.int32),
        "loss_mask": jnp.ones((2, 16), jnp.float32),
    }
    loss, grads = jax.value_and_grad(
        lambda p: compute_loss(runtime, p, batch, rng=jax.random.key(2),
                               deterministic=False))(params)
    assert np.isfinite(float(loss))
    for leaf in jax.tree.leaves(grads):
        assert np.all(np.isfinite(np.asarray(leaf)))
