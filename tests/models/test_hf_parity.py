"""Logit-level parity vs HuggingFace reference implementations.

This is the hermetic version of the reference's trust path
(verify_correctness.py:113-173 + tests/test_llama_weights.py): random tiny
models are built in `transformers` (torch CPU), their weights converted with
the production converters, and logits compared elementwise in fp32.  The
reference asserts avg(max|Δlogit|) ≤ 0.001 on real 7B weights; tiny fp32
models should match much tighter.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from megatron_llm_tpu.config import ModelConfig, PositionEmbeddingType
from megatron_llm_tpu.models import model
from megatron_llm_tpu.tools import hf_interop


def _max_abs_diff(cfg, params, hf_model, tokens):
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(np.asarray(tokens))).logits.float().numpy()
    logits = jax.jit(lambda p, t: model.forward(cfg, p, t))(params, jnp.asarray(tokens))
    logits = np.asarray(logits)[..., : cfg.vocab_size]
    return float(np.max(np.abs(logits - hf_logits)))


def test_llama_parity():
    hf_cfg = transformers.LlamaConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=176,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=64,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf_model = transformers.LlamaForCausalLM(hf_cfg).eval()

    cfg = ModelConfig(
        vocab_size=128,
        hidden_size=64,
        num_layers=3,
        num_attention_heads=4,
        num_kv_heads=2,
        ffn_hidden_size=176,
        max_position_embeddings=64,
        norm_type="rmsnorm",
        norm_eps=1e-5,
        activation="swiglu",
        params_dtype="float32",
        attention_impl="dot",
        recompute="none",
        make_vocab_size_divisible_by=8,
    )
    params = hf_interop.llama_from_hf(hf_model.state_dict(), cfg)
    tokens = np.random.default_rng(0).integers(0, 128, (2, 48))
    diff = _max_abs_diff(cfg, params, hf_model, tokens)
    assert diff < 2e-4, f"llama logit diff {diff}"


def test_llama_rope_scaling_parity():
    """Linear position-interpolation RoPE scaling (Code-Llama style)."""
    hf_cfg = transformers.LlamaConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=176,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=4,
        max_position_embeddings=128,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        rope_scaling={"type": "linear", "factor": 2.0},
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(1)
    hf_model = transformers.LlamaForCausalLM(hf_cfg).eval()
    cfg = ModelConfig(
        vocab_size=128,
        hidden_size=64,
        num_layers=2,
        num_attention_heads=4,
        ffn_hidden_size=176,
        max_position_embeddings=128,
        rope_scaling_factor=2.0,
        params_dtype="float32",
        attention_impl="dot",
        recompute="none",
        make_vocab_size_divisible_by=8,
    )
    params = hf_interop.llama_from_hf(hf_model.state_dict(), cfg)
    tokens = np.random.default_rng(1).integers(0, 128, (1, 64))
    diff = _max_abs_diff(cfg, params, hf_model, tokens)
    assert diff < 2e-4, f"rope-scaled llama logit diff {diff}"


def test_llama_roundtrip_hf():
    """native → HF → native round trip is exact (reference:
    tests/test_llama_weights.py megatron→HF step)."""
    cfg = ModelConfig(
        vocab_size=128, hidden_size=64, num_layers=2, num_attention_heads=4,
        num_kv_heads=2, ffn_hidden_size=176, params_dtype="float32",
        make_vocab_size_divisible_by=8,
    )
    params = model.init_params(jax.random.key(0), cfg)
    sd = hf_interop.llama_to_hf(params, cfg)
    params2 = hf_interop.llama_from_hf(sd, cfg)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(params2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=0)


@pytest.mark.parametrize("new_arch", [False, True], ids=["7b-mqa", "40b-gqa"])
def test_falcon_parity(new_arch):
    kwargs = dict(
        vocab_size=128,
        hidden_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        hidden_dropout=0.0,
        attention_dropout=0.0,
        parallel_attn=True,
        bias=False,
        alibi=False,
        max_position_embeddings=64,
        attn_implementation="eager",
    )
    if new_arch:
        kwargs.update(new_decoder_architecture=True, num_kv_heads=2)
        num_kv = 2
    else:
        kwargs.update(multi_query=True, new_decoder_architecture=False)
        num_kv = 1
    hf_cfg = transformers.FalconConfig(**kwargs)
    torch.manual_seed(2)
    hf_model = transformers.FalconForCausalLM(hf_cfg).eval()

    cfg = ModelConfig(
        vocab_size=128,
        hidden_size=64,
        num_layers=2,
        num_attention_heads=4,
        num_kv_heads=num_kv,
        ffn_hidden_size=256,
        max_position_embeddings=64,
        norm_type="layernorm",
        activation="gelu_exact",
        parallel_attn=True,
        parallel_layernorm=new_arch,
        tie_embed_logits=True,
        params_dtype="float32",
        attention_impl="dot",
        recompute="none",
        make_vocab_size_divisible_by=8,
    )
    params = hf_interop.falcon_from_hf(hf_model.state_dict(), cfg)
    tokens = np.random.default_rng(2).integers(0, 128, (2, 32))
    diff = _max_abs_diff(cfg, params, hf_model, tokens)
    assert diff < 2e-4, f"falcon logit diff {diff}"


def test_gpt2_parity():
    hf_cfg = transformers.GPT2Config(
        vocab_size=128,
        n_positions=64,
        n_embd=64,
        n_layer=2,
        n_head=4,
        resid_pdrop=0.0,
        embd_pdrop=0.0,
        attn_pdrop=0.0,
        attn_implementation="eager",
    )
    torch.manual_seed(3)
    hf_model = transformers.GPT2LMHeadModel(hf_cfg).eval()
    cfg = ModelConfig(
        vocab_size=128,
        hidden_size=64,
        num_layers=2,
        num_attention_heads=4,
        ffn_hidden_size=256,
        max_position_embeddings=64,
        norm_type="layernorm",
        activation="gelu",
        position_embedding_type=PositionEmbeddingType.ABSOLUTE,
        use_bias=True,
        tie_embed_logits=True,
        params_dtype="float32",
        attention_impl="dot",
        recompute="none",
        make_vocab_size_divisible_by=8,
    )
    params = hf_interop.gpt2_from_hf(hf_model.state_dict(), cfg)
    tokens = np.random.default_rng(3).integers(0, 128, (2, 32))
    diff = _max_abs_diff(cfg, params, hf_model, tokens)
    assert diff < 2e-4, f"gpt2 logit diff {diff}"


def test_llama3_shape_parity():
    """Llama-3-style config (GQA 4:1, rope_theta 500k, big-vocab padding)
    through config_from_hf + the converter: logit parity vs transformers.
    The llama3 preset itself is just these capabilities at size."""
    hf_cfg = transformers.LlamaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=112,
        num_hidden_layers=2,
        num_attention_heads=8,
        num_key_value_heads=2,
        max_position_embeddings=64,
        rms_norm_eps=1e-5,
        rope_theta=500000.0,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(3)
    hf_model = transformers.LlamaForCausalLM(hf_cfg).eval()

    cfg = hf_interop.config_from_hf(
        hf_cfg, "llama", params_dtype="float32", attention_impl="dot",
        recompute="none", seq_length=64)
    assert cfg.rope_theta == 500000.0 and cfg.kv_heads == 2
    params = hf_interop.llama_from_hf(hf_model.state_dict(), cfg)
    tokens = np.random.default_rng(5).integers(0, 256, (2, 48))
    diff = _max_abs_diff(cfg, params, hf_model, tokens)
    assert diff < 2e-4, f"llama3-shape logit diff {diff}"


def test_llama3_preset():
    from megatron_llm_tpu.config import llama3_config

    cfg = llama3_config("8b")
    assert cfg.hidden_size == 4096 and cfg.kv_heads == 8
    assert cfg.rope_theta == 500000.0 and cfg.vocab_size == 128256
    assert cfg.ffn_size == 14336
    cfg70 = llama3_config("70b", seq_length=4096,
                          max_position_embeddings=4096)
    assert cfg70.num_layers == 80 and cfg70.kv_heads == 8


def test_llama31_rope_scaling_parity():
    """Llama-3.1 piecewise ("llama3"-type) RoPE scaling: logit parity vs
    transformers on a scaled-context config (original ctx 32 -> 64,
    factor 8) — an extension beyond the reference's linear-PI-only
    scaling."""
    hf_cfg = transformers.LlamaConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=112,
        num_hidden_layers=2,
        num_attention_heads=8,
        num_key_value_heads=2,
        max_position_embeddings=64,
        rms_norm_eps=1e-5,
        rope_theta=500000.0,
        rope_scaling={
            "rope_type": "llama3",
            "factor": 8.0,
            "low_freq_factor": 1.0,
            "high_freq_factor": 4.0,
            "original_max_position_embeddings": 32,
        },
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(11)
    hf_model = transformers.LlamaForCausalLM(hf_cfg).eval()

    cfg = hf_interop.config_from_hf(
        hf_cfg, "llama", params_dtype="float32", attention_impl="dot",
        recompute="none", seq_length=64)
    assert cfg.rope_scaling_type == "llama3"
    assert cfg.rope_original_max_positions == 32
    params = hf_interop.llama_from_hf(hf_model.state_dict(), cfg)
    tokens = np.random.default_rng(7).integers(0, 128, (2, 60))
    diff = _max_abs_diff(cfg, params, hf_model, tokens)
    assert diff < 2e-4, f"llama3.1 rope-scaling logit diff {diff}"


def test_unsupported_rope_scaling_rejected():
    """yarn/dynamic rope types must fail loudly, not silently import as
    linear PI with divergent logits."""
    import pytest as _pytest

    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4,
        rope_scaling={"rope_type": "dynamic", "factor": 2.0},
    )
    with _pytest.raises(ValueError, match="rope_scaling"):
        hf_interop.config_from_hf(hf_cfg, "llama")


def test_yarn_rope_scaling_parity():
    """YaRN (NTK-by-parts) rope scaling incl. the attention temperature
    folded into cos/sin: logit parity vs transformers."""
    hf_cfg = transformers.LlamaConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=112,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        rope_scaling={
            "rope_type": "yarn",
            "factor": 4.0,
            "original_max_position_embeddings": 32,
        },
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(21)
    hf_model = transformers.LlamaForCausalLM(hf_cfg).eval()

    cfg = hf_interop.config_from_hf(
        hf_cfg, "llama", params_dtype="float32", attention_impl="dot",
        recompute="none", seq_length=128)
    assert cfg.rope_scaling_type == "yarn"
    params = hf_interop.llama_from_hf(hf_model.state_dict(), cfg)
    tokens = np.random.default_rng(13).integers(0, 128, (2, 100))
    diff = _max_abs_diff(cfg, params, hf_model, tokens)
    assert diff < 2e-4, f"yarn rope-scaling logit diff {diff}"
