"""Crash-safe checkpointing under injected faults.

Every test kills (or fails) a save at a specific point and proves the
recovery contract: the previous complete checkpoint stays loadable
bitwise, the tracker never goes torn, and the next save cleans up the
wreckage.  One test uses a *real* SIGKILL in a subprocess — the staging +
atomic-rename design must survive an untrappable death, not just a
Python exception.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import jax

from megatron_llm_tpu import checkpointing as ckpt
from megatron_llm_tpu import metrics as metrics_lib
from megatron_llm_tpu.resilience import SimulatedCrash, chaos

pytestmark = pytest.mark.chaos


def _state(v: float):
    """A plain-numpy 'train state' — checkpointing is pytree-generic, so
    fault tests don't need a model (keeps them sub-second)."""
    return {"w": np.full(8, v, np.float32), "step": np.asarray(v, np.int32)}


def _template():
    return {"w": np.zeros(8, np.float32), "step": np.zeros((), np.int32)}


def _assert_loads(root, expect_iter, expect_value):
    state, it = ckpt.load_checkpoint(str(root), _template())
    assert it == expect_iter
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(state["w"])),
        np.full(8, expect_value, np.float32))
    assert int(jax.device_get(state["step"])) == expect_value


def test_tracker_write_is_atomic(tmp_path):
    ckpt.write_tracker(str(tmp_path), 1)
    chaos().crash_at("tracker-replace")
    with pytest.raises(SimulatedCrash):
        ckpt.write_tracker(str(tmp_path), 2)
    # the crash hit between writing the tmp file and the os.replace: the
    # visible tracker is still the old, fully-valid value
    assert ckpt.read_tracker(str(tmp_path)) == 1
    ckpt.write_tracker(str(tmp_path), 2)
    assert ckpt.read_tracker(str(tmp_path)) == 2


@pytest.mark.parametrize("site", [
    "ckpt-staging",      # crash right after the staging dir is created
    "ckpt-pre-commit",   # crash after the orbax write, before the rename
    "ckpt-pre-tracker",  # crash after the rename, before the tracker moves
])
def test_crash_mid_save_leaves_previous_checkpoint(tmp_path, site):
    root = str(tmp_path)
    ckpt.save_checkpoint(root, _state(1), iteration=1)
    chaos().crash_at(site)
    with pytest.raises(SimulatedCrash):
        ckpt.save_checkpoint(root, _state(2), iteration=2)
    # the tracker still points at the last fully-committed save...
    assert ckpt.read_tracker(root) == 1
    if site == "ckpt-pre-tracker":
        # ...even when the new payload did land: commit order is
        # payload-then-tracker, and an unmoved tracker is honored
        assert ckpt.is_complete(root, 2)
    else:
        assert not ckpt.is_complete(root, 2)
    # ...and loading recovers iteration 1 bitwise
    _assert_loads(root, 1, 1)
    # a post-crash save of the same iteration succeeds (stale staging from
    # the crash — if any — is cleared, the torn/duplicate dir is replaced)
    ckpt.save_checkpoint(root, _state(2), iteration=2)
    assert ckpt.read_tracker(root) == 2
    _assert_loads(root, 2, 2)
    assert not list(tmp_path.glob("iter_*" + ckpt.STAGING_SUFFIX))


_KILL_SCRIPT = textwrap.dedent("""
    import numpy as np
    from megatron_llm_tpu import checkpointing as ckpt
    from megatron_llm_tpu.resilience import chaos

    root = {root!r}

    def state(v):
        return {{"w": np.full(8, v, np.float32),
                 "step": np.asarray(v, np.int32)}}

    ckpt.save_checkpoint(root, state(1), iteration=1,
                         meta={{"consumed_samples": 100}})
    chaos().kill_at("ckpt-pre-commit")
    ckpt.save_checkpoint(root, state(2), iteration=2,
                         meta={{"consumed_samples": 200}})
    raise SystemExit("unreachable: the save above must SIGKILL us")
""")


def test_real_sigkill_mid_save_resumes_from_previous(tmp_path):
    """The headline crash-safety proof: a process SIGKILLed in the middle
    of a checkpoint save (after the orbax payload write, before the atomic
    commit) leaves a root from which resume loads the *previous* complete
    checkpoint with its exact params and consumed_samples."""
    root = str(tmp_path / "ckpt")
    script = _KILL_SCRIPT.format(root=root)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == -signal.SIGKILL, (
        f"expected death by SIGKILL, got rc={proc.returncode}\n"
        f"stdout: {proc.stdout}\nstderr: {proc.stderr}")
    # the kill left staging wreckage, never a committed iter_0000002
    assert ckpt.read_tracker(root) == 1
    assert not ckpt.is_complete(root, 2)
    _assert_loads(root, 1, 1)
    assert ckpt.load_meta(root)["consumed_samples"] == 100
    # the next save (fresh process == this one) recovers and commits
    ckpt.save_checkpoint(root, _state(2), iteration=2,
                         meta={"consumed_samples": 200})
    _assert_loads(root, 2, 2)
    assert ckpt.load_meta(root)["consumed_samples"] == 200


def test_io_failure_is_retried(tmp_path):
    root = str(tmp_path)
    chaos().fail_io("ckpt-state-save", times=2)
    ckpt.save_checkpoint(root, _state(1), iteration=1, retries=3)
    assert ckpt.read_tracker(root) == 1
    _assert_loads(root, 1, 1)
    assert metrics_lib.RESILIENCE_EVENTS.get("io_retries") == 2
    assert metrics_lib.RESILIENCE_EVENTS.get("io_giveups") == 0


def test_io_failure_beyond_retries_fails_clean(tmp_path):
    root = str(tmp_path)
    ckpt.save_checkpoint(root, _state(1), iteration=1)
    chaos().fail_io("ckpt-state-save", times=10)
    with pytest.raises(OSError):
        ckpt.save_checkpoint(root, _state(2), iteration=2, retries=3)
    assert metrics_lib.RESILIENCE_EVENTS.get("io_giveups") == 1
    # a *failed* (not killed) save cleans its staging dir and leaves the
    # root exactly as it was
    assert ckpt.read_tracker(root) == 1
    assert not list(tmp_path.glob("iter_*" + ckpt.STAGING_SUFFIX))
    _assert_loads(root, 1, 1)


def test_restore_io_failure_is_retried(tmp_path):
    root = str(tmp_path)
    ckpt.save_checkpoint(root, _state(3), iteration=3)
    chaos().fail_io("ckpt-restore", times=1)
    _assert_loads(root, 3, 3)
    assert metrics_lib.RESILIENCE_EVENTS.get("io_retries") == 1


def test_gc_retention_keeps_newest(tmp_path):
    root = str(tmp_path)
    for it in range(1, 6):
        ckpt.save_checkpoint(root, _state(it), iteration=it, keep=2)
    assert ckpt.list_iterations(root) == [4, 5]
    assert metrics_lib.RESILIENCE_EVENTS.get("checkpoint_gc_deleted") == 3
    _assert_loads(root, 5, 5)


def test_torn_tracker_falls_back_to_scan(tmp_path):
    root = str(tmp_path)
    ckpt.save_checkpoint(root, _state(1), iteration=1)
    ckpt.save_checkpoint(root, _state(2), iteration=2)
    # bitrot / torn write from a pre-atomic writer
    (tmp_path / ckpt.TRACKER_FILENAME).write_text("garb\x00age")
    _assert_loads(root, 2, 2)
    assert metrics_lib.RESILIENCE_EVENTS.get("checkpoint_fallbacks") == 1


def test_tracker_ahead_of_torn_payload_falls_back(tmp_path):
    """Tracker points at an iteration whose payload is torn (crash between
    payload loss and tracker write never happens with the atomic order,
    but a manually-deleted / half-synced dir does): load falls back to the
    newest complete checkpoint instead of crashing the resume."""
    root = str(tmp_path)
    ckpt.save_checkpoint(root, _state(1), iteration=1)
    torn = tmp_path / "iter_0000002" / "state"
    torn.mkdir(parents=True)  # payload dir exists, no orbax markers
    ckpt.write_tracker(root, 2)
    _assert_loads(root, 1, 1)
    assert metrics_lib.RESILIENCE_EVENTS.get("checkpoint_fallbacks") == 1
    # a *pinned* load of the torn iteration still fails hard
    with pytest.raises(FileNotFoundError):
        ckpt.load_checkpoint(root, _template(), iteration=2)


def test_save_checkpoint_writes_config_and_meta_json(tmp_path):
    from megatron_llm_tpu.config import (
        OptimizerConfig, RuntimeConfig, TrainConfig, tiny_config)

    cfg = RuntimeConfig(model=tiny_config(),
                        optimizer=OptimizerConfig(),
                        train=TrainConfig(seq_length=32)).validate()
    root = str(tmp_path)
    ckpt.save_checkpoint(root, _state(1), cfg, iteration=1,
                         meta={"consumed_samples": 7})
    committed = tmp_path / "iter_0000001"
    assert json.loads((committed / "meta.json").read_text()) == {
        "consumed_samples": 7}
    assert (committed / "config.json").exists()
