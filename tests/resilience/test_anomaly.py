"""In-graph anomaly defense: NaN-batch skip and EWMA loss-spike skip.

The train step donates its state buffers, so by the time the host sees a
bad loss the pre-step params are gone — the skip decision therefore lives
*inside* the compiled step (resilience/anomaly.py), and these tests prove
it bitwise: an anomalous step must change nothing but the iteration
counter and the guard/skip bookkeeping.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from megatron_llm_tpu.config import (
    OptimizerConfig,
    RuntimeConfig,
    TrainConfig,
    tiny_config,
)
from megatron_llm_tpu.models import model as model_lib
from megatron_llm_tpu.resilience import poison_nan
from megatron_llm_tpu.training.step import (
    compute_loss,
    init_train_state,
    make_train_step,
)

pytestmark = pytest.mark.chaos

SHAPE = (2, 2, 16)  # [accum, micro, seq]


def _cfg(**train_overrides):
    train = dict(train_iters=50, micro_batch_size=2, global_batch_size=4,
                 seq_length=16)
    train.update(train_overrides)
    return RuntimeConfig(
        model=tiny_config(seq_length=16, max_position_embeddings=16),
        optimizer=OptimizerConfig(lr=1e-3, lr_warmup_iters=2),
        train=TrainConfig(**train),
    ).validate()


def _batch(seed=0, vocab=256):
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, vocab, SHAPE)
    return {
        "tokens": jnp.asarray(toks, jnp.int32),
        "labels": jnp.asarray(np.roll(toks, -1, -1), jnp.int32),
        "loss_mask": jnp.ones(SHAPE, jnp.float32),
    }


def _fresh_state(cfg, seed=0):
    params = model_lib.init_params(jax.random.key(seed), cfg.model)
    return init_train_state(cfg, params)


def _snapshot(state):
    """Host copies of everything an anomalous step must preserve bitwise
    (taken BEFORE the step — donation invalidates the device buffers)."""
    return jax.device_get({"params": state.params, "mu": state.opt.mu,
                           "nu": state.opt.nu, "step": state.opt.step})


def _assert_bitwise(snapshot, state):
    after = jax.device_get({"params": state.params, "mu": state.opt.mu,
                            "nu": state.opt.nu, "step": state.opt.step})
    for name in ("params", "mu", "nu"):
        for a, b in zip(jax.tree.leaves(snapshot[name]),
                        jax.tree.leaves(after[name])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(after["step"]) == int(snapshot["step"])


def test_nan_batch_skips_step_bitwise():
    cfg = _cfg()
    step = make_train_step(cfg)
    state = _fresh_state(cfg)
    state, m = step(state, _batch(0), None)
    assert int(m["skipped"]) == 0 and int(m["anomaly"]) == 0

    snap = _snapshot(state)
    state, m = step(state, poison_nan(_batch(1)), None)
    assert int(m["skipped"]) == 1
    assert int(m["anomaly"]) == 1
    assert int(m["anomaly_run"]) == 1
    assert not np.isfinite(float(m["loss"]))
    assert int(state.iteration) == 2      # time advances...
    assert int(state.skipped) == 1
    _assert_bitwise(snap, state)          # ...the model does not

    # a clean step afterwards updates params and resets the anomaly run
    state, m = step(state, _batch(2), None)
    assert int(m["skipped"]) == 0
    assert int(m["anomaly_run"]) == 0
    after = jax.device_get(jax.tree.leaves(state.params)[0])
    assert not np.array_equal(np.asarray(after),
                              np.asarray(jax.tree.leaves(snap["params"])[0]))


def _boost_loss_fn(cfg, p, mb, rng, deterministic):
    """compute_loss plus a per-microbatch constant from the batch — lets a
    test inject an exact, finite loss spike (a deterministic finite spike
    is not constructible from token data at a random init)."""
    return (compute_loss(cfg, p, mb, rng, deterministic)
            + jnp.sum(mb["boost"]))


def _boosted(seed, boost_total):
    b = _batch(seed)
    per_elem = boost_total / (SHAPE[1] * SHAPE[2])
    b["boost"] = jnp.full(SHAPE, per_elem, jnp.float32)
    return b


def test_loss_spike_skips_step_bitwise():
    cfg = _cfg(anomaly_z_threshold=4.0, anomaly_warmup_steps=3,
               anomaly_ewma_alpha=0.2)
    step = make_train_step(cfg, loss_fn=_boost_loss_fn)
    state = _fresh_state(cfg)
    losses = []
    for i in range(4):  # clean warmup: fills the EWMA stats
        state, m = step(state, _boosted(i, 0.0), None)
        losses.append(float(m["loss"]))
        assert int(m["anomaly"]) == 0, f"warmup step {i} flagged"
    assert int(state.guard.steps) == 4

    snap = _snapshot(state)
    state, m = step(state, _boosted(9, 50.0), None)  # +50 over a ~5.5 EWMA
    assert np.isfinite(float(m["loss"]))  # finite — this is a SPIKE skip
    assert float(m["loss"]) > max(losses) + 40
    assert int(m["anomaly"]) == 1
    assert int(m["skipped"]) == 1
    assert int(m["anomaly_run"]) == 1
    _assert_bitwise(snap, state)

    # EWMA stats did not absorb the spike: an identical clean step is
    # accepted right after
    state, m = step(state, _boosted(4, 0.0), None)
    assert int(m["anomaly"]) == 0
    assert int(m["anomaly_run"]) == 0


def test_no_spike_flagging_during_warmup():
    cfg = _cfg(anomaly_z_threshold=4.0, anomaly_warmup_steps=3,
               anomaly_ewma_alpha=0.2)
    step = make_train_step(cfg, loss_fn=_boost_loss_fn)
    state = _fresh_state(cfg)
    state, m = step(state, _boosted(0, 0.0), None)
    assert int(m["anomaly"]) == 0
    # a huge but finite jump at step 2 — before warmup completes — must
    # not be flagged (the EWMA has no trustworthy baseline yet)
    snap_w = np.asarray(
        jax.device_get(jax.tree.leaves(state.params)[0]))
    state, m = step(state, _boosted(1, 50.0), None)
    assert int(m["anomaly"]) == 0
    assert int(m["skipped"]) == 0
    after_w = np.asarray(jax.device_get(jax.tree.leaves(state.params)[0]))
    assert not np.array_equal(snap_w, after_w)  # the step was applied


def test_spike_detection_disabled_by_default():
    cfg = _cfg()  # anomaly_z_threshold defaults to 0.0 == off
    assert cfg.train.anomaly_z_threshold == 0.0
    step = make_train_step(cfg, loss_fn=_boost_loss_fn)
    state = _fresh_state(cfg)
    for i in range(25):  # far past any warmup
        state, m = step(state, _boosted(i, 0.0), None)
    state, m = step(state, _boosted(30, 50.0), None)
    assert int(m["anomaly"]) == 0
    assert int(m["skipped"]) == 0


def test_nan_anomaly_does_not_poison_ewma():
    """A NaN loss must not corrupt the spike baseline: after a NaN skip,
    normal losses keep being accepted."""
    cfg = _cfg(anomaly_z_threshold=4.0, anomaly_warmup_steps=2,
               anomaly_ewma_alpha=0.2)
    step = make_train_step(cfg)
    state = _fresh_state(cfg)
    for i in range(3):
        state, m = step(state, _batch(i), None)
    state, m = step(state, poison_nan(_batch(7)), None)
    assert int(m["anomaly"]) == 1
    for i in range(3, 6):
        state, m = step(state, _batch(i), None)
        assert int(m["anomaly"]) == 0, "EWMA poisoned by the NaN step"
        assert np.isfinite(float(m["loss"]))
