"""Fault-injection test bootstrap: the chaos controller and the resilience
event counters are process-global, so every test starts and ends disarmed
— a leaked armed fault would fail an unrelated test far from the cause."""

import pytest

from megatron_llm_tpu import metrics as metrics_lib
from megatron_llm_tpu.resilience import chaos


@pytest.fixture(autouse=True)
def clean_chaos():
    chaos().reset()
    metrics_lib.RESILIENCE_EVENTS.reset()
    yield
    chaos().reset()
    metrics_lib.RESILIENCE_EVENTS.reset()
