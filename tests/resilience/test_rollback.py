"""Rollback-after-K-anomalies: driver integration and unit behavior.

The scenario: a poisoned stretch of the corpus NaNs every loss for longer
than per-step skips should tolerate.  After K consecutive data anomalies
the driver restores the last complete checkpoint but keeps
consumed_samples where it is — the replayed iterations therefore read
*past* the poisoned window and the run completes clean.
"""

import numpy as np
import pytest

import jax

from megatron_llm_tpu import checkpointing as ckpt
from megatron_llm_tpu import metrics as metrics_lib
from megatron_llm_tpu.config import (
    OptimizerConfig,
    RuntimeConfig,
    TrainConfig,
    tiny_config,
)
from megatron_llm_tpu.resilience import poison_nan
from megatron_llm_tpu.training.driver import (
    pretrain,
    rollback_to_last_checkpoint,
)

pytestmark = pytest.mark.chaos

SEQ = 16
GBS = 4  # accum=2 x micro=2 x dp=1


def _cfg(tmp_path, **train_overrides):
    train = dict(train_iters=8, micro_batch_size=2, global_batch_size=GBS,
                 seq_length=SEQ, save=str(tmp_path / "ckpt"),
                 save_interval=3, log_interval=1)
    train.update(train_overrides)
    return RuntimeConfig(
        model=tiny_config(num_layers=1, hidden_size=32,
                          num_attention_heads=2, num_kv_heads=2,
                          ffn_hidden_size=64, vocab_size=128,
                          seq_length=SEQ, max_position_embeddings=SEQ),
        optimizer=OptimizerConfig(lr=1e-3, lr_warmup_iters=2),
        train=TrainConfig(**train),
    ).validate()


def _sample_batch(pos, vocab):
    """Deterministic batch covering samples [pos, pos+GBS)."""
    rng = np.random.default_rng(1000 + pos)
    toks = rng.integers(0, vocab, (2, 2, SEQ))
    return {
        "tokens": toks.astype(np.int32),
        "labels": np.roll(toks, -1, -1).astype(np.int32),
        "loss_mask": np.ones((2, 2, SEQ), np.float32),
    }


def _poisoned_provider(vocab, lo, hi):
    """batch_provider whose samples in [lo, hi) are NaN-poisoned — the
    poison follows the DATA position, exactly like a bad corpus shard, so
    post-rollback replays (same iteration numbers, fresh data) are clean."""
    def provider(consumed, gbs):
        assert gbs == GBS
        pos = consumed
        while True:
            batch = _sample_batch(pos, vocab)
            if pos < hi and pos + gbs > lo:
                batch = poison_nan(batch)
            pos += gbs
            yield batch
    return provider


def test_rollback_after_k_anomalies_skips_poisoned_window(tmp_path):
    """save@3 (consumed 12) → iters 4-5 poisoned (samples 12..20) → after
    K=2 consecutive anomalies the driver restores iteration 3 and resumes
    on samples 20.. — the final run reaches train_iters with finite params
    and a consumed_samples count that proves the poison window was passed,
    not re-read."""
    cfg = _cfg(tmp_path, anomaly_rollback_after=2)
    provider = _poisoned_provider(cfg.model.vocab_size, 12, 20)
    state = pretrain(cfg, batch_provider=provider)

    assert int(state.iteration) == 8
    assert metrics_lib.RESILIENCE_EVENTS.get("rollbacks") == 1
    # 8 productive + 2 poisoned-then-rolled-back iterations of data
    meta = ckpt.load_meta(cfg.train.save)
    assert meta["consumed_samples"] == 10 * GBS
    # the poisoned steps were never applied: everything stayed finite
    for leaf in jax.tree.leaves(jax.device_get(state.params)):
        assert np.isfinite(np.asarray(leaf)).all()
    # the skip counter lives in TrainState, so the rollback restored it to
    # the checkpoint's value — and no post-rollback step was anomalous
    assert int(state.skipped) == 0


def test_rollback_writes_anchor_checkpoint_when_none_exists(tmp_path):
    """With rollback armed and an empty save dir, the driver saves an
    iteration-0 anchor before training so there is always something to
    roll back to."""
    cfg = _cfg(tmp_path, train_iters=1, save_interval=100,
               anomaly_rollback_after=2)
    provider = _poisoned_provider(cfg.model.vocab_size, -1, -1)  # no poison
    assert ckpt.latest_complete_iteration(cfg.train.save) is None
    pretrain(cfg, batch_provider=provider)
    assert ckpt.is_complete(cfg.train.save, 0)


def test_rollback_restores_checkpoint_bitwise(tmp_path):
    """Unit contract of rollback_to_last_checkpoint: the returned state is
    the checkpointed one, bit for bit."""
    cfg = _cfg(tmp_path)
    root = cfg.train.save
    saved = {"w": np.arange(16, dtype=np.float32),
             "step": np.asarray(5, np.int32)}
    ckpt.save_checkpoint(root, saved, iteration=5)
    diverged = {"w": np.full(16, np.nan, np.float32),
                "step": np.asarray(9, np.int32)}
    restored, it = rollback_to_last_checkpoint(cfg, diverged)
    assert it == 5
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(restored["w"])), saved["w"])
    assert metrics_lib.RESILIENCE_EVENTS.get("rollbacks") == 1


def test_rollback_gives_up_after_max_rollbacks(tmp_path):
    cfg = _cfg(tmp_path, anomaly_max_rollbacks=2)
    ckpt.save_checkpoint(cfg.train.save, {"w": np.zeros(4, np.float32)},
                         iteration=1)
    with pytest.raises(RuntimeError, match="giving up"):
        rollback_to_last_checkpoint(cfg, {"w": np.ones(4, np.float32)},
                                    attempt=3)


def test_rollback_without_checkpoint_root_fails_loudly(tmp_path):
    cfg = _cfg(tmp_path, save=None)
    assert cfg.train.load is None
    with pytest.raises(RuntimeError, match="checkpoint root"):
        rollback_to_last_checkpoint(cfg, {"w": np.ones(4, np.float32)})


def test_driver_resumes_past_torn_checkpoint(tmp_path):
    """Driver-level torn-checkpoint recovery: the tracker points at a torn
    iteration (crash aftermath); resume falls back to the newest complete
    checkpoint and finishes training."""
    cfg = _cfg(tmp_path, train_iters=2, save_interval=2)
    provider = _poisoned_provider(cfg.model.vocab_size, -1, -1)
    pretrain(cfg, batch_provider=provider)
    assert ckpt.read_tracker(cfg.train.save) == 2

    # fake the aftermath of a crash-after-commit-before-tracker bug plus a
    # half-synced payload: a torn newer checkpoint the tracker points at
    torn = tmp_path / "ckpt" / "iter_0000003" / "state"
    torn.mkdir(parents=True)
    ckpt.write_tracker(cfg.train.save, 3)

    cfg2 = _cfg(tmp_path, train_iters=4, save_interval=100,
                load=str(tmp_path / "ckpt"))
    state = pretrain(cfg2, batch_provider=provider)
    # resumed from 2 (the newest COMPLETE checkpoint), not 3, and the
    # fallback was counted
    assert metrics_lib.RESILIENCE_EVENTS.get("checkpoint_fallbacks") >= 1
    assert int(state.iteration) == 4
    assert ckpt.load_meta(cfg2.train.save)["consumed_samples"] == 4 * GBS
