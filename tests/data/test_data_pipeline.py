"""Data pipeline tests: .bin/.idx format, index helpers (native == python),
GPT dataset semantics, blending, samplers, instruction masks."""

import numpy as np
import pytest

from megatron_llm_tpu.data import index_helpers
from megatron_llm_tpu.data.blendable_dataset import (
    BlendableDataset,
    parse_data_paths,
)
from megatron_llm_tpu.data.gpt_dataset import (
    GPTDataset,
    build_gpt_datasets,
    get_train_valid_test_split,
)
from megatron_llm_tpu.data.indexed_dataset import (
    MMapIndexedDataset,
    MMapIndexedDatasetBuilder,
    write_dataset,
)
from megatron_llm_tpu.data.samplers import BatchIterator, PretrainingSampler
from megatron_llm_tpu.data.instruction_dataset import InstructionDataset, Role


@pytest.fixture
def corpus(tmp_path):
    rng = np.random.default_rng(0)
    docs = [rng.integers(0, 1000, rng.integers(5, 60)).astype(np.int32)
            for _ in range(50)]
    prefix = str(tmp_path / "corpus")
    write_dataset(prefix, docs, dtype=np.int32)
    return prefix, docs


def test_mmap_roundtrip(corpus):
    prefix, docs = corpus
    ds = MMapIndexedDataset(prefix)
    assert len(ds) == len(docs)
    for i in [0, 7, 49]:
        np.testing.assert_array_equal(ds[i], docs[i])
    # partial reads
    np.testing.assert_array_equal(ds.get(3, offset=2, length=3),
                                  docs[3][2:5])


def test_format_is_reference_compatible(corpus):
    """Parse the .idx with the reference's documented byte layout."""
    import struct

    prefix, docs = corpus
    with open(prefix + ".idx", "rb") as f:
        assert f.read(9) == b"MMIDIDX\x00\x00"
        assert struct.unpack("<Q", f.read(8)) == (1,)
        (code,) = struct.unpack("<B", f.read(1))
        assert code == 4  # int32 (reference dtype table)
        (n,) = struct.unpack("<Q", f.read(8))
        (dc,) = struct.unpack("<Q", f.read(8))
        assert n == len(docs)
        assert dc == len(docs) + 1
        sizes = np.frombuffer(f.read(4 * n), np.int32)
        np.testing.assert_array_equal(sizes, [len(d) for d in docs])
        pointers = np.frombuffer(f.read(8 * n), np.int64)
        assert pointers[0] == 0
        assert pointers[1] == sizes[0] * 4


def test_builder_merge(tmp_path):
    a = [np.arange(5, dtype=np.int32), np.arange(3, dtype=np.int32)]
    b = [np.arange(7, dtype=np.int32)]
    write_dataset(str(tmp_path / "a"), a)
    write_dataset(str(tmp_path / "b"), b)
    m = MMapIndexedDatasetBuilder(str(tmp_path / "m"), np.int32)
    m.merge_file(str(tmp_path / "a"))
    m.merge_file(str(tmp_path / "b"))
    m.finalize()
    ds = MMapIndexedDataset(str(tmp_path / "m"))
    assert len(ds) == 3
    np.testing.assert_array_equal(ds[2], b[0])


def test_native_helpers_match_python():
    lib = index_helpers.get_lib()
    assert lib is not None, "native helper library failed to build"
    rng = np.random.default_rng(1)
    sizes = rng.integers(3, 50, 40).astype(np.int32)
    doc_idx = np.tile(np.arange(40, dtype=np.int32), 3)
    rng.shuffle(doc_idx)
    tokens_per_epoch = int(sizes.sum())
    for seq in (16, 31):
        native = index_helpers.build_sample_idx(
            sizes, doc_idx, seq, 3, tokens_per_epoch)
        py = index_helpers.build_sample_idx_py(
            sizes, doc_idx, seq, 3, tokens_per_epoch)
        np.testing.assert_array_equal(native, py)

    w = np.asarray([0.3, 0.5, 0.2])
    di_n, si_n = index_helpers.build_blending_indices(w, 500)
    di_p, si_p = index_helpers.build_blending_indices_py(w, 500)
    np.testing.assert_array_equal(di_n, di_p)
    np.testing.assert_array_equal(si_n, si_p)
    # achieved ratios ≈ weights
    counts = np.bincount(di_n, minlength=3) / 500
    np.testing.assert_allclose(counts, w, atol=0.01)


def test_gpt_dataset_samples(corpus, tmp_path):
    prefix, docs = corpus
    indexed = MMapIndexedDataset(prefix)
    documents = np.arange(len(docs), dtype=np.int32)
    seq = 32
    ds = GPTDataset("train", indexed, documents, num_samples=40,
                    seq_length=seq, seed=5, cache_dir=str(tmp_path / "cache"))
    assert len(ds) >= 40
    flat = {}
    # every sample has seq+1 tokens drawn from the flattened shuffled corpus
    s0 = ds[0]["text"]
    assert s0.shape == (seq + 1,)
    # adjacent samples share the boundary token: sample i's tokens are a
    # contiguous window; verify against a manual flattening of doc_idx
    concat = np.concatenate([docs[d] for d in np.asarray(ds.doc_idx)])
    for i in range(5):
        idx = int(ds.shuffle_idx[i])
        start_tok = idx * seq
        np.testing.assert_array_equal(
            ds[np.where(np.asarray(ds.shuffle_idx) == idx)[0][0]]["text"],
            concat[start_tok:start_tok + seq + 1])


def test_gpt_dataset_cache_reused(corpus, tmp_path):
    prefix, docs = corpus
    indexed = MMapIndexedDataset(prefix)
    documents = np.arange(len(docs), dtype=np.int32)
    cache = str(tmp_path / "cache2")
    ds1 = GPTDataset("t", indexed, documents, 20, 16, 7, cache)
    ds2 = GPTDataset("t", indexed, documents, 20, 16, 7, cache)
    np.testing.assert_array_equal(np.asarray(ds1.shuffle_idx),
                                  np.asarray(ds2.shuffle_idx))
    np.testing.assert_array_equal(ds1[3]["text"], ds2[3]["text"])


def test_split_string():
    assert get_train_valid_test_split("969,30,1", 1000) == [0, 969, 999, 1000]
    assert get_train_valid_test_split("100,0,0", 50) == [0, 50, 50, 50]


def test_build_gpt_datasets(corpus, tmp_path):
    prefix, docs = corpus
    train, valid, test = build_gpt_datasets(
        prefix, "8,1,1", (30, 5, 5), seq_length=16, seed=3,
        cache_dir=str(tmp_path / "c3"))
    assert train is not None and valid is not None and test is not None
    assert len(train) >= 30


def test_blendable(corpus, tmp_path):
    prefix, docs = corpus
    indexed = MMapIndexedDataset(prefix)
    documents = np.arange(len(docs), dtype=np.int32)
    a = GPTDataset("a", indexed, documents, 20, 16, 1, str(tmp_path / "ca"))
    b = GPTDataset("b", indexed, documents, 20, 16, 2, str(tmp_path / "cb"))
    blend = BlendableDataset([a, b], [0.7, 0.3], size=30)
    assert len(blend) == 30
    sample = blend[0]
    assert sample["text"].shape == (17,)
    counts = np.bincount(blend.dataset_index, minlength=2) / 30
    assert abs(counts[0] - 0.7) < 0.1
    assert parse_data_paths(["0.3", "x", "0.7", "y"]) == ([0.3, 0.7], ["x", "y"])


def test_pretraining_sampler_resumes():
    s = PretrainingSampler(total_samples=100, consumed_samples=0,
                           batch_size=10)
    batches = list(s)
    assert len(batches) == 10
    s2 = PretrainingSampler(total_samples=100, consumed_samples=30,
                            batch_size=10)
    batches2 = list(s2)
    assert batches2[0] == batches[3]


def test_batch_iterator_shapes(corpus, tmp_path):
    prefix, docs = corpus
    indexed = MMapIndexedDataset(prefix)
    documents = np.arange(len(docs), dtype=np.int32)
    ds = GPTDataset("bi", indexed, documents, 24, 16, 1, str(tmp_path / "cc"))
    it = BatchIterator(ds, global_batch_size=8, grad_accum=2, seq_length=16,
                       eod_token=999)
    batch = next(iter(it))
    assert batch["tokens"].shape == (2, 4, 16)
    assert batch["labels"].shape == (2, 4, 16)
    assert batch["loss_mask"].shape == (2, 4, 16)
    np.testing.assert_array_equal(
        batch["labels"][..., :-1], batch["tokens"][..., 1:])
    # eod labels are masked out of the loss
    assert np.all(batch["loss_mask"][batch["labels"] == 999] == 0)


def test_instruction_dataset(tmp_path):
    text_docs, role_docs = [], []
    rng = np.random.default_rng(0)
    for _ in range(10):
        n_sys, n_user, n_asst = rng.integers(2, 6, 3)
        text_docs.append(rng.integers(5, 100, n_sys + n_user + n_asst))
        role_docs.append(np.concatenate([
            np.full(n_sys, Role.system), np.full(n_user, Role.prompter),
            np.full(n_asst, Role.assistant)]))
    write_dataset(str(tmp_path / "i_text_document"), text_docs, np.int32)
    write_dataset(str(tmp_path / "i_role_document"), role_docs, np.int64)
    from megatron_llm_tpu.data.instruction_dataset import (
        build_instruction_datasets,
    )

    train, valid, test = build_instruction_datasets(
        str(tmp_path / "i"), "8,1,1", seq_length=12, seed=0, pad_token=0,
        scalar_loss_mask=0.25)
    s = train[0]
    assert s["tokens"].shape == (12,)
    assert s["loss_mask"].shape == (12,)
    # mask values ∈ {1.0 (assistant), 0.25 (context), 0.0 (pad)}
    assert set(np.unique(s["loss_mask"])) <= {0.0, 0.25, 1.0}
