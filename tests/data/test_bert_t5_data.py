"""BERT/T5 dataset + mapping-builder tests (reference: bert_dataset.py,
t5_dataset.py, helpers.cpp build_mapping)."""

import numpy as np
import pytest

from megatron_llm_tpu.data.bert_dataset import BertDataset, BertSpecialTokens
from megatron_llm_tpu.data.index_helpers import (
    build_bert_mapping,
    build_bert_mapping_py,
    get_lib,
)
from megatron_llm_tpu.data.indexed_dataset import (
    MMapIndexedDataset,
    MMapIndexedDatasetBuilder,
)
from megatron_llm_tpu.data.t5_dataset import T5Dataset, T5SpecialTokens

VOCAB = 96
SPECIAL = BertSpecialTokens(cls=90, sep=91, mask=92, pad=0)


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """12 documents of 3-6 sentences of 4-12 tokens each."""
    path = tmp_path_factory.mktemp("corpus") / "sentences"
    rng = np.random.default_rng(0)
    builder = MMapIndexedDatasetBuilder(str(path), dtype=np.int32)
    for _ in range(12):
        for _ in range(int(rng.integers(3, 7))):
            builder.add_item(rng.integers(1, 80, int(rng.integers(4, 13))))
        builder.end_document()
    builder.finalize()
    return MMapIndexedDataset(str(path))


def _check_mapping(mapping, ds, max_tokens):
    assert len(mapping) > 0
    doc_bounds = np.asarray(ds.doc_idx)
    for start, end, target in mapping:
        assert end - start >= 2  # room for an A/B split
        assert 2 <= target <= max_tokens
        # sample never crosses a document boundary
        doc = np.searchsorted(doc_bounds, start, side="right") - 1
        assert end <= doc_bounds[doc + 1]


def test_build_bert_mapping_invariants(corpus):
    mapping = build_bert_mapping(
        np.asarray(corpus.sizes), np.asarray(corpus.doc_idx),
        max_num_tokens=29, short_seq_prob=0.3, num_epochs=2, seed=1)
    _check_mapping(mapping, corpus, 29)


def test_build_bert_mapping_native_matches_invariants(corpus):
    """Native lib (when present) satisfies the same contract as the numpy
    fallback; sentence coverage per epoch is identical."""
    if get_lib() is None:
        pytest.skip("no native helper lib")
    native = build_bert_mapping(
        np.asarray(corpus.sizes), np.asarray(corpus.doc_idx),
        max_num_tokens=29, short_seq_prob=0.0, num_epochs=1, seed=1)
    fallback = build_bert_mapping_py(
        np.asarray(corpus.sizes, np.int32),
        np.asarray(corpus.doc_idx, np.int64),
        max_num_tokens=29, short_seq_prob=0.0, num_epochs=1, seed=1)
    _check_mapping(native, corpus, 29)
    # with short_seq_prob=0 the packing is deterministic → same row
    # multiset regardless of PRNG-specific shuffle order
    key = lambda m: sorted(map(tuple, np.asarray(m)))
    assert key(native) == key(fallback)


def test_bert_dataset_sample_contract(corpus):
    ds = BertDataset(corpus, seq_length=32, vocab_size=VOCAB,
                     special=SPECIAL, seed=3)
    n_random = 0
    for i in range(min(len(ds), 40)):
        s = ds[i]
        assert s["tokens"].shape == (32,)
        assert s["tokens"][0] == SPECIAL.cls
        content = int(s["pad_mask"].sum())
        assert s["tokens"][content - 1] == SPECIAL.sep
        # masked positions carry the original token in labels
        masked = s["loss_mask"] > 0
        assert masked.sum() >= 1
        # pad region is zero-masked
        assert (s["loss_mask"][content:] == 0).all()
        assert (s["tokentype_ids"][:content] <= 1).all()
        n_random += int(s["is_random"])
        # at masked positions where tokens == MASK, label != MASK
        mask_positions = masked & (s["tokens"] == SPECIAL.mask)
        assert (s["labels"][mask_positions] != SPECIAL.mask).all()
    assert 0 < n_random < 40  # both NSP classes appear


def test_bert_dataset_deterministic(corpus):
    a = BertDataset(corpus, 32, VOCAB, SPECIAL, seed=5)
    b = BertDataset(corpus, 32, VOCAB, SPECIAL, seed=5)
    for i in range(min(len(a), 10)):
        for k in a[i]:
            np.testing.assert_array_equal(a[i][k], b[i][k])


def test_t5_dataset_sample_contract(corpus):
    sp = T5SpecialTokens(bos=1, eos=2, pad=0)
    ds = T5Dataset(corpus, enc_seq_length=32, dec_seq_length=24,
                   vocab_size=VOCAB, special=sp, max_sentinels=8, seed=4)
    assert len(ds) > 0
    for i in range(min(len(ds), 20)):
        s = ds[i]
        assert s["enc_tokens"].shape == (32,)
        assert s["dec_tokens"].shape == (24,)
        assert s["labels"].shape == (24,)
        assert s["dec_tokens"][0] == sp.bos
        # decoder input is labels shifted right by one (teacher forcing)
        n_lab = int(s["loss_mask"].sum())
        np.testing.assert_array_equal(s["dec_tokens"][1:n_lab],
                                      s["labels"][: n_lab - 1])
        # sentinels (top-of-vocab ids) appear in encoder and labels
        assert (s["enc_tokens"] >= VOCAB - 8).any()
        assert (s["labels"][: n_lab] >= VOCAB - 8).any() or \
            s["labels"][n_lab - 1] == sp.eos


def test_t5_reconstruction_roundtrip(corpus):
    """Merging encoder non-noise tokens with label spans at matching
    sentinels reproduces the original token stream."""
    sp = T5SpecialTokens(bos=1, eos=2, pad=0)
    ds = T5Dataset(corpus, enc_seq_length=64, dec_seq_length=64,
                   vocab_size=VOCAB, special=sp, max_sentinels=8, seed=9)
    s = ds[0]
    start, end, target = (int(x) for x in ds.mapping[0])
    orig = np.concatenate(
        [np.asarray(corpus[i]) for i in range(start, end)])[:target]

    enc = s["enc_tokens"][s["enc_pad_mask"] > 0]
    labels = s["labels"][s["loss_mask"] > 0]
    # split labels into sentinel-prefixed spans
    spans = {}
    cur = None
    for t in labels:
        if t >= VOCAB - 8 and t != sp.eos:
            cur = int(t)
            spans[cur] = []
        elif t == sp.eos:
            cur = None
        elif cur is not None:
            spans[cur].append(int(t))
    rebuilt = []
    for t in enc:
        if int(t) in spans:
            rebuilt.extend(spans[int(t)])
        else:
            rebuilt.append(int(t))
    np.testing.assert_array_equal(np.asarray(rebuilt), orig)
