"""build_blocks_mapping: exact ICT/REALM block packing
(reference megatron/data/helpers.cpp:454-694)."""

import numpy as np
import pytest

from megatron_llm_tpu.data.index_helpers import (
    build_blocks_mapping,
    build_blocks_mapping_py,
    get_lib,
)


def _corpus():
    # 4 docs: doc0 3 sents, doc1 1 sent (skipped unless one-sent), doc2 has
    # a long sentence (always skipped), doc3 5 sents
    sent_sizes = np.asarray(
        [5, 6, 7,            # doc 0
         4,                  # doc 1
         5, 600,             # doc 2 — long sentence
         3, 3, 3, 3, 3],     # doc 3
        np.int32)
    doc_sent_idx = np.asarray([0, 3, 4, 6, 11], np.int64)
    title_sizes = np.asarray([2, 0, 1, 4], np.int32)
    return doc_sent_idx, sent_sizes, title_sizes


def test_packing_semantics():
    doc_sent_idx, sent_sizes, title_sizes = _corpus()
    rows = build_blocks_mapping_py(doc_sent_idx, sent_sizes, title_sizes,
                                   num_epochs=1, max_num_samples=2**62,
                                   max_seq_length=10, seed=3)
    assert len(rows) > 0
    docs_seen = set()
    for start, end, doc, block_id in rows:
        docs_seen.add(int(doc))
        assert end > start
        # block sentences all inside the doc
        assert doc_sent_idx[doc] <= start and end <= doc_sent_idx[doc + 1]
    # doc1 (one sentence) and doc2 (long sentence) must be absent
    assert 1 not in docs_seen
    assert 2 not in docs_seen
    assert {0, 3} <= docs_seen
    # target shrinks by the title: doc0 target = 10-2 = 8 → sents 5+6 ≥ 8
    # with 1 remaining... must respect min 2 sentences per block
    for start, end, doc, _ in rows:
        assert end - start >= 1


def test_one_sent_blocks_includes_single_sentence_docs():
    doc_sent_idx, sent_sizes, title_sizes = _corpus()
    rows = build_blocks_mapping_py(doc_sent_idx, sent_sizes, title_sizes,
                                   num_epochs=1, max_num_samples=2**62,
                                   max_seq_length=10, seed=3,
                                   use_one_sent_blocks=True)
    assert 1 in {int(r[2]) for r in rows}


def test_native_matches_fallback_packing():
    """Native and numpy fallback must produce the same *set* of blocks
    (shuffle streams differ: mt19937_64 vs numpy Generator)."""
    if get_lib() is None:
        pytest.skip("native library unavailable")
    doc_sent_idx, sent_sizes, title_sizes = _corpus()
    kw = dict(num_epochs=2, max_num_samples=2**62, max_seq_length=10,
              seed=7)
    native = build_blocks_mapping(doc_sent_idx, sent_sizes, title_sizes,
                                  **kw)
    fallback = build_blocks_mapping_py(doc_sent_idx, sent_sizes,
                                       title_sizes, **kw)
    assert len(native) == len(fallback)
    as_set = lambda rows: {tuple(int(x) for x in r) for r in rows}
    assert as_set(native) == as_set(fallback)


def test_max_num_samples_caps_at_epoch_boundary():
    doc_sent_idx, sent_sizes, title_sizes = _corpus()
    one_epoch = build_blocks_mapping_py(
        doc_sent_idx, sent_sizes, title_sizes, num_epochs=1,
        max_num_samples=2**62, max_seq_length=10, seed=3)
    capped = build_blocks_mapping_py(
        doc_sent_idx, sent_sizes, title_sizes, num_epochs=10,
        max_num_samples=len(one_epoch), max_seq_length=10, seed=3)
    # the reference checks the cap between epochs, so one full extra epoch
    # may be emitted after the cap is reached
    assert len(one_epoch) <= len(capped) <= 2 * len(one_epoch)
