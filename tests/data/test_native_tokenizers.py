"""Native BPE/WordPiece tokenizers vs ``transformers`` on the SAME
vocabulary files (no network: the files are synthesized here, then loaded
by both implementations).

Reference parity: megatron/tokenizer/gpt2_tokenization.py and
bert_tokenization.py read vocab files natively; round 2 shipped these via
HF AutoTokenizer only (flagged acceptable-but-partial in the verdict).
"""

import json

import pytest

from megatron_llm_tpu.tokenizer.bpe import (GPT2BPETokenizer,
                                            WordPieceTokenizer,
                                            bytes_to_unicode)
from megatron_llm_tpu.tokenizer.tokenizer import build_tokenizer


# ---------------------------------------------------------------------------
# fixtures: small but real vocab/merges built from a corpus
# ---------------------------------------------------------------------------


def _make_gpt2_files(tmp_path):
    """Train a tiny byte-level BPE with huggingface tokenizers if
    available, else hand-construct a deterministic merge list."""
    byte_vocab = list(bytes_to_unicode().values())
    merges = [
        ("h", "e"), ("l", "l"), ("ll", "o"), ("he", "llo"),
        ("w", "o"), ("r", "l"), ("wo", "rl"), ("worl", "d"),
        ("Ġ", "world"), ("Ġ", "hello"), ("t", "h"), ("th", "e"),
        ("Ġ", "the"), ("1", "2"), ("12", "3"),
    ]
    vocab_toks = list(byte_vocab)
    for a, b in merges:
        vocab_toks.append(a + b)
    vocab_toks.append("<|endoftext|>")
    vocab = {t: i for i, t in enumerate(vocab_toks)}
    vf = tmp_path / "vocab.json"
    mf = tmp_path / "merges.txt"
    vf.write_text(json.dumps(vocab), encoding="utf-8")
    mf.write_text("#version: 0.2\n" +
                  "\n".join(f"{a} {b}" for a, b in merges) + "\n",
                  encoding="utf-8")
    return str(vf), str(mf)


SAMPLES = [
    "hello world",
    "the hello worlds",
    "Hello, WORLD! 123",
    "hello\nworld\tand more",
    "unicode café — dash",
    "   leading spaces",
    "don't we've it's",
    "x² y 5½ Ⅻ",     # No/Nl number chars: \p{N}-vs-\d split differences
]


def test_gpt2_bpe_matches_transformers(tmp_path):
    vf, mf = _make_gpt2_files(tmp_path)
    transformers = pytest.importorskip("transformers")
    hf = transformers.GPT2Tokenizer(vocab_file=vf, merges_file=mf)
    ours = GPT2BPETokenizer(vf, mf)
    for s in SAMPLES:
        got = ours.encode(s)
        want = hf.encode(s, add_special_tokens=False)
        assert got == want, (s, got, want)
        assert ours.decode(got) == hf.decode(want)


def test_gpt2_bpe_roundtrip_bytes(tmp_path):
    vf, mf = _make_gpt2_files(tmp_path)
    ours = GPT2BPETokenizer(vf, mf)
    for s in SAMPLES:
        assert ours.decode(ours.encode(s)) == s


def test_gpt2_native_build_tokenizer(tmp_path):
    _make_gpt2_files(tmp_path)
    tok = build_tokenizer("gpt2-bpe", str(tmp_path))
    ids = tok.tokenize("hello world")
    assert tok.detokenize(ids) == "hello world"
    assert tok.eod == tok.vocab_size - 1  # <|endoftext|> is last


# ---------------------------------------------------------------------------
# WordPiece
# ---------------------------------------------------------------------------


_BERT_VOCAB = [
    "[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
    "the", "quick", "brown", "fox", "jump", "##s", "##ed", "##ing",
    "over", "lazy", "dog", "hello", "world", "un", "##believ", "##able",
    ",", ".", "!", "?", "'", "123", "##45", "caf", "##e",
]


def _make_bert_vocab(tmp_path):
    f = tmp_path / "vocab.txt"
    f.write_text("\n".join(_BERT_VOCAB) + "\n", encoding="utf-8")
    return str(f)


BERT_SAMPLES = [
    "The quick brown fox jumps over the lazy dog",
    "hello world!",
    "unbelievable, unbelievable.",
    "jumped jumping jumps",
    "café 12345",
    "UNKNOWNWORD here",  # 'here' is OOV too -> [UNK]
    "hello\tworld\nfox",           # Cc whitespace must separate words
    "[MASK] hello [SEP]",          # never_split specials stay intact
    "the " + "quick" * 30,         # >100 chars -> [UNK] like the reference
]


def test_wordpiece_matches_transformers(tmp_path):
    vf = _make_bert_vocab(tmp_path)
    transformers = pytest.importorskip("transformers")
    hf = transformers.BertTokenizer(vocab_file=vf, do_lower_case=True)
    ours = WordPieceTokenizer(vf, lower_case=True)
    for s in BERT_SAMPLES:
        got = ours.encode(s)
        want = hf.encode(s, add_special_tokens=False)
        assert got == want, (s, got, want)


def test_wordpiece_special_ids(tmp_path):
    vf = _make_bert_vocab(tmp_path)
    tok = build_tokenizer("bert-wordpiece", vf)
    assert tok.pad == 0 and tok.cls == 2 and tok.sep == 3 and tok.mask == 4
    ids = tok.tokenize("hello world")
    assert tok.detokenize(ids) == "hello world"


def test_wordpiece_unk_and_subwords(tmp_path):
    vf = _make_bert_vocab(tmp_path)
    ours = WordPieceTokenizer(vf, lower_case=True)
    vocab = ours.vocab
    assert ours.encode("jumps") == [vocab["jump"], vocab["##s"]]
    assert ours.encode("zzzz") == [vocab["[UNK]"]]


def test_crlf_vocab_files_parse_identically(tmp_path):
    """Windows-saved merges.txt/vocab.txt (CRLF) must not corrupt ranks
    or token strings."""
    vf, mf = _make_gpt2_files(tmp_path)
    crlf_m = tmp_path / "merges_crlf.txt"
    crlf_m.write_bytes(open(mf, "rb").read().replace(b"\n", b"\r\n"))
    a = GPT2BPETokenizer(vf, mf)
    b = GPT2BPETokenizer(vf, str(crlf_m))
    for s in SAMPLES:
        assert a.encode(s) == b.encode(s)

    bvf = _make_bert_vocab(tmp_path)
    crlf_v = tmp_path / "vocab_crlf.txt"
    crlf_v.write_bytes(open(bvf, "rb").read().replace(b"\n", b"\r\n"))
    wa = WordPieceTokenizer(bvf)
    wb = WordPieceTokenizer(str(crlf_v))
    assert wa.vocab == wb.vocab


def test_gpt2_bpe_randomized_parity(tmp_path):
    """200 randomized strings (mixed scripts, numbers, punctuation,
    whitespace runs) must encode identically to transformers."""
    import random

    vf, mf = _make_gpt2_files(tmp_path)
    transformers = pytest.importorskip("transformers")
    hf = transformers.GPT2Tokenizer(vocab_file=vf, merges_file=mf)
    ours = GPT2BPETokenizer(vf, mf)
    rng = random.Random(1234)
    pieces = ["hello", "world", "the", "don't", "123", "²", "½", "¡",
              "é", "ß", "中", ",", ".", "!", "  ", " ", "\n", "\t", "--"]
    for _ in range(200):
        s = "".join(rng.choice(pieces)
                    for _ in range(rng.randrange(0, 12)))
        got, want = ours.encode(s), hf.encode(s, add_special_tokens=False)
        assert got == want, (repr(s), got, want)
        assert ours.decode(got) == hf.decode(want), repr(s)


def test_wordpiece_randomized_parity(tmp_path):
    vf = _make_bert_vocab(tmp_path)
    transformers = pytest.importorskip("transformers")
    hf = transformers.BertTokenizer(vocab_file=vf, do_lower_case=True)
    ours = WordPieceTokenizer(vf, lower_case=True)
    import random

    rng = random.Random(99)
    pieces = ["the", "quick", "Fox", "jumps", "unbelievable", "café",
              "12345", "[MASK]", "zzz", ",", "!", "?", " ", "\t", "\n",
              "'", "over-the", "dog."]
    for _ in range(200):
        s = " ".join(rng.choice(pieces)
                     for _ in range(rng.randrange(0, 10)))
        got, want = ours.encode(s), hf.encode(s, add_special_tokens=False)
        assert got == want, (repr(s), got, want)


def test_cpp_engine_matches_python_merge_loop(tmp_path):
    """The C++ merge engine and the pure-Python loop must produce
    identical ids (and both match transformers, covered above)."""
    vf, mf = _make_gpt2_files(tmp_path)
    native = GPT2BPETokenizer(vf, mf, use_native=True)
    if native._native is None:
        pytest.skip("native bpe engine unavailable (no toolchain)")
    python = GPT2BPETokenizer(vf, mf, use_native=False)
    import random

    rng = random.Random(7)
    pieces = ["hello", "world", "the", "123", " ", "é", "中", "!",
              "<|endoftext|>", "x"]
    for _ in range(300):
        s = "".join(rng.choice(pieces)
                    for _ in range(rng.randrange(0, 14)))
        assert native.encode(s) == python.encode(s), repr(s)
