"""Microbatch-calculator semantics (reference megatron/microbatches.py)."""

import pytest

from megatron_llm_tpu.training.microbatches import (
    ConstantNumMicroBatches,
    RampupBatchsizeNumMicroBatches,
    build_num_microbatches_calculator,
)


def test_constant():
    c = ConstantNumMicroBatches(
        global_batch_size=64, micro_batch_size=4, data_parallel_size=2)
    assert c.get() == 8
    assert c.get_current_global_batch_size() == 64
    c.update(10_000, True)  # no-op
    assert c.get() == 8


def test_constant_divisibility_enforced():
    with pytest.raises(AssertionError):
        ConstantNumMicroBatches(65, 4, 2)


def test_rampup_schedule():
    # start 8, +8 per rung, over 64 samples, target 32: rungs at 8,16,24,32
    c = RampupBatchsizeNumMicroBatches(
        start_batch_size=8, batch_size_increment=8, ramup_samples=64,
        global_batch_size=32, micro_batch_size=4, data_parallel_size=1)
    assert c.get_current_global_batch_size() == 8
    assert c.get() == 2
    # 3 increments over 64 samples → one rung every 64/3 samples
    c.update(22, True)
    assert c.get_current_global_batch_size() == 16
    c.update(43, True)
    assert c.get_current_global_batch_size() == 24
    c.update(64, True)
    assert c.get_current_global_batch_size() == 32
    c.update(1_000_000, True)
    assert c.get_current_global_batch_size() == 32
    assert c.get() == 8


def test_rampup_resume_midway():
    """Resume from consumed_samples lands on the correct rung."""
    c = build_num_microbatches_calculator(
        32, 4, 1, rampup_batch_size=[8, 8, 64])
    c.update(30, True)
    assert c.get_current_global_batch_size() == 16


def test_rampup_degenerate():
    """start == target and zero ramp samples must not divide by zero."""
    c = build_num_microbatches_calculator(8, 4, 1, [8, 8, 64])
    assert c.get_current_global_batch_size() == 8
    c2 = build_num_microbatches_calculator(32, 4, 1, [8, 8, 0])
    c2.update(0, True)
    assert c2.get_current_global_batch_size() == 32


def test_builder_dispatch():
    c = build_num_microbatches_calculator(16, 2, 2)
    assert isinstance(c, ConstantNumMicroBatches)
    r = build_num_microbatches_calculator(16, 2, 2, [4, 4, 100])
    assert isinstance(r, RampupBatchsizeNumMicroBatches)
