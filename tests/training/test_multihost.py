"""Two-process multihost dryrun (tools/multihost_dryrun.py) as a CI test.

Covers the multi-process paths single-process tests cannot reach:
jax.distributed rendezvous via initialize.initialize_distributed, a global
mesh with dp spanning processes, per-process data feeding, the
_cluster_any signal consensus, and coordinated orbax save/load
(VERDICT round 1, next-step #5).
"""

import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]


def test_two_process_dryrun():
    env = dict(os.environ)
    # The launcher sets per-worker JAX env itself; make sure nothing from
    # the test session's single-process config leaks through.
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "multihost_dryrun.py")],
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert '"multihost": "ok"' in proc.stdout
    assert '"processes": 2' in proc.stdout
