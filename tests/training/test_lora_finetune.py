"""LoRA finetuning (training/lora.py): factor-only training against a
frozen base, serving-identical epilogue math, and the adapter-only
checkpoint hand-off to the serving registry."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.config import (
    OptimizerConfig,
    RuntimeConfig,
    TrainConfig,
    tiny_config,
)
from megatron_llm_tpu.models import model as model_lib
from megatron_llm_tpu.ops import lora as lora_lib
from megatron_llm_tpu.training.lora import (
    _check_targets,
    lora_finetune,
    make_lora_step,
)


class MockDataset:
    def __init__(self, vocab, seq, n=256, seed=0):
        self.vocab, self.seq, self.n, self.seed = vocab, seq, n, seed

    def __len__(self):
        return self.n

    def __getitem__(self, idx):
        rng = np.random.default_rng(self.seed + idx)
        return {"text": rng.integers(0, self.vocab, self.seq + 1)
                .astype(np.int64)}


def _cfg(**train_overrides):
    train = dict(train_iters=6, micro_batch_size=2, global_batch_size=4,
                 seq_length=16, log_interval=0)
    train.update(train_overrides)
    return RuntimeConfig(
        model=tiny_config(num_layers=2, vocab_size=64,
                          make_vocab_size_divisible_by=8),
        optimizer=OptimizerConfig(lr=5e-2, clip_grad=1.0,
                                  lr_warmup_iters=1),
        train=TrainConfig(**train),
    ).validate()


def test_loss_decreases_and_base_stays_frozen():
    cfg = _cfg()
    base = model_lib.init_params(jax.random.key(0), cfg.model)
    base_copy = jax.tree.map(np.asarray, base)
    adapter = lora_lib.init_lora_adapter(cfg.model, jax.random.key(1),
                                         rank=4)
    step = make_lora_step(cfg, base, adapter)

    # one fixed batch, repeated: loss on it must fall as the factors
    # move (overfit-a-batch, the classic optimizer smoke)
    rng = np.random.default_rng(3)
    toks = rng.integers(1, cfg.model.vocab_size,
                        (2, 2, cfg.train.seq_length)).astype(np.int32)
    batch = {
        "tokens": jnp.asarray(toks),
        "labels": jnp.asarray(np.roll(toks, -1, axis=-1)),
        "loss_mask": jnp.ones((2, 2, cfg.train.seq_length), jnp.float32),
    }
    from megatron_llm_tpu.training import optimizer as opt_lib

    factors = adapter.factors
    opt_state = opt_lib.init_opt_state(factors, cfg.optimizer)
    losses = []
    for it in range(8):
        factors, opt_state, m = step(factors, opt_state, batch,
                                     jnp.int32(it))
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], losses
    # the base never moved — only the factor tree trains
    for want, got in zip(jax.tree.leaves(base_copy),
                         jax.tree.leaves(jax.tree.map(np.asarray, base))):
        np.testing.assert_array_equal(want, got)
    # B departed from zero-init
    assert np.any(np.asarray(factors["wq"]["b"]) != 0)


def test_lora_finetune_end_to_end(tmp_path):
    cfg = _cfg()
    base = model_lib.init_params(jax.random.key(0), cfg.model)
    ds = MockDataset(cfg.model.vocab_size, cfg.train.seq_length)
    trained = lora_finetune(cfg, base, ds, rank=4, alpha=16.0,
                            save=str(tmp_path))
    assert trained.rank == 4 and trained.alpha == 16.0
    # adapter-only checkpoint round-trips and registers for serving
    back = lora_lib.load_adapter(str(tmp_path / "adapter"))
    for t in trained.targets:
        np.testing.assert_array_equal(np.asarray(back.factors[t]["b"]),
                                      np.asarray(trained.factors[t]["b"]))
    from megatron_llm_tpu.serving import AdapterRegistry

    reg = AdapterRegistry(cfg.model, n_slots=2, rank=4)
    reg.register("trained", back)
    assert reg.known("trained")


def test_training_epilogue_is_the_serving_epilogue():
    """A trained adapter applied via the serving arena must reproduce
    the exact delta the training loss saw: forward(lora=single-slot
    arena with α/r folded) == the loss_fn's own forward."""
    cfg = _cfg()
    base = model_lib.init_params(jax.random.key(0), cfg.model)
    ad = lora_lib.init_lora_adapter(cfg.model, jax.random.key(1), rank=4,
                                    alpha=8.0)
    # non-zero B so the delta is live
    import dataclasses

    ad = dataclasses.replace(ad, factors={
        t: {"a": f["a"],
            "b": jax.random.normal(jax.random.key(9), f["b"].shape,
                                   f["b"].dtype) * 0.1}
        for t, f in ad.factors.items()})
    toks = jnp.asarray([[3, 5, 7, 11]], jnp.int32)
    # training-side: scale folded into B, all-ones mask, Sr = r
    arenas_t = {t: {"a": f["a"], "b": f["b"] * jnp.float32(ad.scale)}
                for t, f in ad.factors.items()}
    mask_t = jnp.ones((1, ad.rank), jnp.float32)
    out_train = model_lib.forward(cfg.model, base, toks,
                                  lora=(arenas_t, mask_t))
    # serving-side: install into a slot arena, slot mask
    arenas_s = lora_lib.make_arenas(cfg.model, 2, ad.rank, ad.targets)
    arenas_s = lora_lib.install_adapter(arenas_s, ad.factors, 1,
                                        ad.scale, ad.rank)
    mask_s = lora_lib.slot_mask(jnp.asarray([1], jnp.int32), 2, ad.rank)
    out_serve = model_lib.forward(cfg.model, base, toks,
                                  lora=(arenas_s, mask_s))
    np.testing.assert_allclose(np.asarray(out_train),
                               np.asarray(out_serve),
                               atol=1e-5, rtol=1e-5)


def test_moe_mlp_targets_rejected():
    cfg = _cfg()
    import dataclasses

    moe_model = dataclasses.replace(cfg.model, num_experts=4)
    moe_cfg = dataclasses.replace(cfg, model=moe_model)
    with pytest.raises(ValueError, match="MoE"):
        _check_targets(moe_cfg, ("wq", "w_up"))
    _check_targets(moe_cfg, ("wq", "wv"))   # attention targets are fine
