"""pretrain_bert.py / pretrain_t5.py entry-point smoke tests: a few real
iterations end-to-end (dataset → loss_fn → optimizer → checkpoint)."""

import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

from megatron_llm_tpu.data.indexed_dataset import MMapIndexedDatasetBuilder


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    path = tmp_path_factory.mktemp("corpus") / "sentences"
    rng = np.random.default_rng(0)
    builder = MMapIndexedDatasetBuilder(str(path), dtype=np.int32)
    for _ in range(30):
        for _ in range(int(rng.integers(3, 7))):
            builder.add_item(rng.integers(1, 80, int(rng.integers(6, 14))))
        builder.end_document()
    builder.finalize()
    return str(path)


def test_pretrain_bert_entrypoint(corpus, tmp_path):
    import pretrain_bert

    state = pretrain_bert.main([
        "--data_path", corpus,
        "--vocab_size", "96",
        "--hidden_size", "32", "--num_layers", "2",
        "--num_attention_heads", "4",
        "--seq_length", "48",
        "--micro_batch_size", "2", "--global_batch_size", "4",
        "--train_iters", "3", "--log_interval", "1",
        "--save", str(tmp_path / "bert_ckpt"),
    ])
    assert int(state.iteration) == 3
    assert (tmp_path / "bert_ckpt").exists()


def test_pretrain_t5_entrypoint(corpus, tmp_path):
    import pretrain_t5

    state = pretrain_t5.main([
        "--data_path", corpus,
        "--vocab_size", "96",
        "--hidden_size", "32", "--num_layers", "2",
        "--num_attention_heads", "4",
        "--encoder_seq_length", "48", "--decoder_seq_length", "24",
        "--micro_batch_size", "2", "--global_batch_size", "4",
        "--train_iters", "3", "--log_interval", "1",
    ])
    assert int(state.iteration) == 3


def test_pretrain_ict_entrypoint(corpus, tmp_path):
    import pretrain_ict

    state = pretrain_ict.main([
        "--data_path", corpus,
        "--vocab_size", "96",
        "--hidden_size", "32", "--num_layers", "2",
        "--num_attention_heads", "4",
        "--query_seq_length", "16", "--block_seq_length", "48",
        "--projection_dim", "16",
        "--micro_batch_size", "4", "--global_batch_size", "4",
        "--train_iters", "3", "--log_interval", "1",
    ])
    assert int(state.iteration) == 3


def test_pretrain_t5_entrypoint_tensor_parallel(corpus, tmp_path):
    """T5 through the FULL parallel stack (tp=2 × dp=2): params + ZeRO-1
    optimizer state sharded by t5_param_specs (VERDICT r3 missing #3 — the
    reference trains T5 through the same TP machinery as GPT)."""
    import pretrain_t5

    state = pretrain_t5.main([
        "--data_path", corpus,
        "--vocab_size", "96",
        "--hidden_size", "32", "--num_layers", "2",
        "--num_attention_heads", "4",
        "--encoder_seq_length", "48", "--decoder_seq_length", "24",
        "--micro_batch_size", "2", "--global_batch_size", "4",
        "--train_iters", "3", "--log_interval", "1",
        "--data_parallel", "2", "--tensor_parallel", "2",
        "--use_distributed_optimizer",
    ])
    assert int(state.iteration) == 3
    # params must actually be tp-sharded, not replicated
    word = state.params["embedding"]["word"]
    assert "tp" in str(word.sharding.spec)
    # ZeRO-1: Adam moments sharded over dp, not replicated
    mu_word = state.opt.mu["embedding"]["word"]
    assert "dp" in str(mu_word.sharding.spec)


def test_pretrain_bert_entrypoint_tensor_parallel(corpus, tmp_path):
    import pretrain_bert

    state = pretrain_bert.main([
        "--data_path", corpus,
        "--vocab_size", "96",
        "--hidden_size", "32", "--num_layers", "2",
        "--num_attention_heads", "4",
        "--seq_length", "48",
        "--micro_batch_size", "2", "--global_batch_size", "4",
        "--train_iters", "3", "--log_interval", "1",
        "--data_parallel", "2", "--tensor_parallel", "2",
    ])
    assert int(state.iteration) == 3
    word = state.params["embedding"]["word"]
    assert "tp" in str(word.sharding.spec)


def test_pretrain_ict_entrypoint_tensor_parallel(corpus, tmp_path):
    """ICT biencoder through tp=2 × dp=2 (both towers sharded by
    biencoder_param_specs)."""
    import pretrain_ict

    state = pretrain_ict.main([
        "--data_path", corpus,
        "--vocab_size", "96",
        "--hidden_size", "32", "--num_layers", "2",
        "--num_attention_heads", "4",
        "--query_seq_length", "16", "--block_seq_length", "48",
        "--projection_dim", "16",
        "--micro_batch_size", "4", "--global_batch_size", "8",
        "--train_iters", "3", "--log_interval", "1",
        "--data_parallel", "2", "--tensor_parallel", "2",
        "--use_distributed_optimizer",
    ])
    assert int(state.iteration) == 3
    word = state.params["query"]["embedding"]["word"]
    assert "tp" in str(word.sharding.spec)
    # ZeRO-1 reaches the two-tower tree: moments sharded over dp
    mu_word = state.opt.mu["query"]["embedding"]["word"]
    assert "dp" in str(mu_word.sharding.spec)


def test_pretrain_t5_entrypoint_split_rank_pipeline(corpus, tmp_path):
    """T5 through the split-rank pipeline (pp=2: 1 encoder stage + 1
    decoder stage) × dp=2 with ZeRO-1 — the reference's
    pipeline_model_parallel_split_rank path (core/parallel_state.py:
    110-112) end-to-end through the entry point, incl. checkpoint save."""
    import pretrain_t5

    state = pretrain_t5.main([
        "--data_path", corpus,
        "--vocab_size", "96",
        "--hidden_size", "32", "--num_layers", "2",
        "--num_attention_heads", "4",
        "--encoder_seq_length", "48", "--decoder_seq_length", "24",
        "--micro_batch_size", "1", "--global_batch_size", "4",
        "--train_iters", "3", "--log_interval", "1",
        "--data_parallel", "2", "--pipeline_parallel", "2",
        "--use_distributed_optimizer",
        "--save", str(tmp_path / "t5_pp_ckpt"),
    ])
    assert int(state.iteration) == 3
    # stage-stacked layers sharded over pp
    wq = state.params["layers"]["attn"]["wq"]
    assert "pp" in str(wq.sharding.spec)
    # encoder stages' dummy cross weights stay exactly zero through
    # optimizer steps (their cotangents are masked to zero)
    import numpy as np

    cross_wo = np.asarray(state.params["cross"]["wo"])
    assert np.abs(cross_wo[0]).max() == 0.0
    assert np.abs(cross_wo[1]).max() > 0.0
    assert (tmp_path / "t5_pp_ckpt").exists()


def test_pretrain_bert_entrypoint_pipeline(corpus, tmp_path):
    """BERT through the encoder pipeline (pp=2 × tp=2)."""
    import pretrain_bert

    state = pretrain_bert.main([
        "--data_path", corpus,
        "--vocab_size", "96",
        "--hidden_size", "32", "--num_layers", "4",
        "--num_attention_heads", "4",
        "--seq_length", "48",
        "--micro_batch_size", "2", "--global_batch_size", "4",
        "--train_iters", "3", "--log_interval", "1",
        "--pipeline_parallel", "2", "--tensor_parallel", "2",
    ])
    assert int(state.iteration) == 3
    wq = state.params["layers"]["attn"]["wq"]
    spec = str(wq.sharding.spec)
    assert "pp" in spec and "tp" in spec


def test_pretrain_custom_pipelined_eval_path(corpus):
    """The pipelined validation branch of pretrain_custom (eval_jit built
    from pipeline_loss_fn on a [1, micro_total, ...] microbatch group)
    must actually run — entry-point defaults never reach it (eval_interval
    1000 vs 3 iters)."""
    import jax

    from megatron_llm_tpu.config import (
        ModelConfig, OptimizerConfig, ParallelConfig, RuntimeConfig,
        TrainConfig,
    )
    from megatron_llm_tpu.data.bert_dataset import (
        BertDataset, BertSpecialTokens,
    )
    from megatron_llm_tpu.data.indexed_dataset import MMapIndexedDataset
    from megatron_llm_tpu.models import encdec
    from megatron_llm_tpu.parallel import pipeline_encdec as pe
    from megatron_llm_tpu.training.driver import pretrain_custom

    model = ModelConfig(
        vocab_size=96, hidden_size=32, num_layers=2,
        num_attention_heads=4, num_kv_heads=4, ffn_hidden_size=64,
        max_position_embeddings=48, norm_type="layernorm",
        activation="gelu", position_embedding_type="absolute",
        use_bias=True, tie_embed_logits=True, tokentype_size=2,
        seq_length=48,
    ).validate()
    parallel = ParallelConfig(pipeline_parallel=2,
                              num_microbatches=2).validate()
    cfg = RuntimeConfig(
        model=model, parallel=parallel,
        optimizer=OptimizerConfig(lr=1e-4, clip_grad=1.0),
        train=TrainConfig(train_iters=2, micro_batch_size=1,
                          global_batch_size=2, seq_length=48,
                          eval_interval=1, eval_iters=1, log_interval=1),
    ).validate()
    special = BertSpecialTokens(cls=92, sep=93, mask=94, pad=0)
    ds = BertDataset(MMapIndexedDataset(corpus), 48, 96, special, seed=0)
    params = pe.bert_to_pipeline_params(
        encdec.init_bert_params(jax.random.key(0), model), parallel)
    specs = pe.bert_pipeline_param_specs(model, parallel)
    state = pretrain_custom(cfg, ds, params, None, valid_dataset=ds,
                            param_specs=specs,
                            pipeline_loss_fn=pe.bert_pipeline_loss)
    assert int(state.iteration) == 2
