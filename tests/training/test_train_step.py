"""Train-step tests: optimizer math vs optax, schedules vs reference
formulas, loss decreases, NaN-skip semantics."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from megatron_llm_tpu.config import (
    ModelConfig,
    OptimizerConfig,
    ParallelConfig,
    RuntimeConfig,
    TrainConfig,
    tiny_config,
)
from megatron_llm_tpu.models import model as model_lib
from megatron_llm_tpu.training import optimizer as opt_lib
from megatron_llm_tpu.training import schedule
from megatron_llm_tpu.training.step import (
    TrainState,
    init_train_state,
    make_train_step,
)


def _toy_cfg(**model_overrides):
    return RuntimeConfig(
        model=tiny_config(**model_overrides),
        parallel=ParallelConfig(),
        optimizer=OptimizerConfig(
            lr=1e-3, min_lr=1e-4, lr_warmup_iters=2, lr_decay_style="cosine",
            clip_grad=1.0, weight_decay=0.1,
        ),
        train=TrainConfig(train_iters=20, micro_batch_size=2,
                          global_batch_size=4, seq_length=16),
    ).validate()


def _toy_batch(cfg, accum=2, seed=0):
    rng = np.random.default_rng(seed)
    shape = (accum, cfg.train.micro_batch_size, cfg.train.seq_length)
    tokens = rng.integers(0, cfg.model.vocab_size, shape)
    return {
        "tokens": jnp.asarray(tokens, jnp.int32),
        "labels": jnp.asarray(np.roll(tokens, -1, axis=-1), jnp.int32),
        "loss_mask": jnp.ones(shape, jnp.float32),
    }


def test_adamw_matches_optax():
    """Our fused AdamW == optax.adamw on an fp32 param tree."""
    cfg = OptimizerConfig(lr=1e-2, weight_decay=0.0, adam_beta1=0.9,
                          adam_beta2=0.95, adam_eps=1e-8, clip_grad=0.0)
    key = jax.random.key(0)
    params = {"w": jax.random.normal(key, (4, 8)),
              "attn": {"wq": jax.random.normal(jax.random.fold_in(key, 1), (8, 8))}}
    grads = jax.tree.map(lambda p: jnp.ones_like(p) * 0.1, params)

    state = opt_lib.init_opt_state(params, cfg)
    ours = params
    ref_opt = optax.adamw(1e-2, b1=0.9, b2=0.95, eps=1e-8, weight_decay=0.0)
    ref_state = ref_opt.init(params)
    theirs = params
    for _ in range(5):
        ours, state = opt_lib.adamw_step(
            cfg, ours, grads, state, jnp.float32(1e-2), jnp.float32(0.0))
        updates, ref_state = ref_opt.update(grads, ref_state, theirs)
        theirs = optax.apply_updates(theirs, updates)
    for a, b in zip(jax.tree.leaves(ours), jax.tree.leaves(theirs)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)


def test_weight_decay_mask():
    """Norm scales and biases are excluded from decay (reference:
    optimizer/__init__.py _get_params_for_weight_decay_optimization)."""
    params = {
        "layers": {
            "input_norm": {"scale": jnp.ones((4,))},
            "attn": {"wq": jnp.ones((4, 4)), "bq": jnp.ones((4,))},
            "mlp": {"w_up": jnp.ones((4, 4)), "b_up": jnp.ones((4,))},
        },
    }
    mask = opt_lib._wd_mask(params)
    assert mask["layers"]["input_norm"]["scale"] == 0.0
    assert mask["layers"]["attn"]["bq"] == 0.0
    assert mask["layers"]["attn"]["wq"] == 1.0
    assert mask["layers"]["mlp"]["b_up"] == 0.0
    assert mask["layers"]["mlp"]["w_up"] == 1.0


def test_lr_schedules():
    cfg = OptimizerConfig(lr=1.0, min_lr=0.1, lr_warmup_iters=10,
                          lr_decay_style="cosine")
    # warmup: linear ramp
    np.testing.assert_allclose(
        float(schedule.learning_rate(cfg, 4, 100)), 0.5, rtol=1e-6)
    # end of decay: min_lr
    np.testing.assert_allclose(
        float(schedule.learning_rate(cfg, 99, 100)), 0.1, rtol=1e-2)
    # midpoint of cosine: (max+min)/2
    np.testing.assert_allclose(
        float(schedule.learning_rate(cfg, 55, 100)), 0.55, rtol=1e-2)
    lin = OptimizerConfig(lr=1.0, min_lr=0.0, lr_warmup_iters=0,
                          lr_decay_style="linear")
    np.testing.assert_allclose(
        float(schedule.learning_rate(lin, 50, 101)), 0.5, rtol=2e-2)
    isr = OptimizerConfig(lr=1.0, min_lr=0.0, lr_warmup_iters=4,
                          lr_decay_style="inverse-square-root")
    np.testing.assert_allclose(
        float(schedule.learning_rate(isr, 15, 100)), 2.0 / 4.0, rtol=1e-6)


def test_loss_decreases():
    cfg = _toy_cfg()
    params = model_lib.init_params(jax.random.key(0), cfg.model)
    state = init_train_state(cfg, params)
    step = make_train_step(cfg)
    batch = _toy_batch(cfg)
    rng = jax.random.key(42)
    losses = []
    for _ in range(10):
        state, metrics = step(state, batch, rng)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.9, losses
    assert int(state.iteration) == 10
    assert int(state.skipped) == 0


def test_bf16_params_fp32_master():
    cfg = _toy_cfg(params_dtype="bfloat16")
    params = model_lib.init_params(jax.random.key(0), cfg.model)
    state = init_train_state(cfg, params)
    assert state.opt.master is not None
    assert jax.tree.leaves(state.opt.master)[0].dtype == jnp.float32
    step = make_train_step(cfg)
    batch = _toy_batch(cfg)
    state2, metrics = step(state, batch, jax.random.key(0))
    # params remain bf16, master stays fp32
    assert jax.tree.leaves(state2.params)[0].dtype == jnp.bfloat16
    assert jax.tree.leaves(state2.opt.master)[0].dtype == jnp.float32
    assert np.isfinite(metrics["loss"])


def test_nan_grad_skips_update():
    cfg = _toy_cfg()
    params = model_lib.init_params(jax.random.key(0), cfg.model)
    state = init_train_state(cfg, params)
    step = make_train_step(cfg)
    batch = _toy_batch(cfg)
    # poison the tokens' loss mask with inf so grads go non-finite
    bad = dict(batch)
    bad["loss_mask"] = batch["loss_mask"] * jnp.inf
    before = jax.tree.map(lambda x: np.asarray(x), state.params)
    state2, metrics = step(state, bad, jax.random.key(0))
    assert int(metrics["skipped"]) == 1
    assert int(state2.skipped) == 1
    after = jax.tree.map(lambda x: np.asarray(x), state2.params)
    for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
        np.testing.assert_array_equal(a, b)
    # optimizer step counter did not advance
    assert int(state2.opt.step) == 0


def test_grad_clipping_applied():
    cfg = _toy_cfg()
    params = model_lib.init_params(jax.random.key(0), cfg.model)
    grads = jax.tree.map(lambda p: jnp.full(p.shape, 10.0, jnp.float32), params)
    clipped, norm = opt_lib.clip_by_global_norm(grads, 1.0)
    new_norm = opt_lib.global_grad_norm(clipped)
    assert float(norm) > 1.0
    np.testing.assert_allclose(float(new_norm), 1.0, rtol=1e-4)


def test_dynamic_scaler_intermittent_overflow_backs_off():
    """Hysteresis accumulates across intermittent overflows and is restored
    only on growth (reference grad_scaler.py:86-106)."""
    import jax.numpy as jnp

    cfg = OptimizerConfig(initial_loss_scale=2.0**16, hysteresis=2,
                          loss_scale_window=1000, min_loss_scale=1.0)
    s = opt_lib.init_dynamic_scaler(cfg)
    t, f = jnp.asarray(True), jnp.asarray(False)
    # alternating inf/ok: hysteresis must reach 0 on the 2nd inf → backoff
    s = opt_lib.scaler_update(s, t, cfg)     # hyst 2→1
    s = opt_lib.scaler_update(s, f, cfg)     # clean, no growth → hyst stays 1
    assert int(s.hysteresis) == 1
    s = opt_lib.scaler_update(s, t, cfg)     # hyst 1→0 → backoff
    assert float(s.scale) == 2.0**15
    # growth after a full clean window restores hysteresis
    cfg2 = OptimizerConfig(initial_loss_scale=2.0**8, hysteresis=2,
                           loss_scale_window=3, min_loss_scale=1.0)
    s = opt_lib.init_dynamic_scaler(cfg2)
    s = opt_lib.scaler_update(s, t, cfg2)    # hyst → 1
    for _ in range(3):
        s = opt_lib.scaler_update(s, f, cfg2)
    assert float(s.scale) == 2.0**9          # grew
    assert int(s.hysteresis) == 2            # restored on growth only
