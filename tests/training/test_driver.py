"""End-to-end driver tests on the 8-device CPU mesh: pretrain loop,
checkpoint resume, eval hooks, batch-size ramp, fault injection
(reference training.py:55-169,654-770 behaviors)."""

import numpy as np
import pytest

from megatron_llm_tpu import checkpointing
from megatron_llm_tpu.config import (
    OptimizerConfig,
    ParallelConfig,
    RuntimeConfig,
    TrainConfig,
    tiny_config,
)
from megatron_llm_tpu.training.driver import pretrain, setup_train_state
from megatron_llm_tpu.utils.timers import Timers


class MockDataset:
    def __init__(self, vocab, seq, n=512, seed=0):
        self.vocab, self.seq, self.n, self.seed = vocab, seq, n, seed

    def __len__(self):
        return self.n

    def __getitem__(self, idx):
        rng = np.random.default_rng(self.seed + idx)
        return {"text": rng.integers(0, self.vocab, self.seq + 1)
                .astype(np.int64)}


def _cfg(tmp_path, **train_overrides):
    train = dict(
        train_iters=4,
        micro_batch_size=2,
        global_batch_size=8,
        seq_length=32,
        eval_interval=2,
        eval_iters=2,
        save=str(tmp_path / "ckpt"),
        save_interval=100,
        log_interval=2,
        metrics=("perplexity", "accuracy"),
    )
    train.update(train_overrides)
    return RuntimeConfig(
        model=tiny_config(),
        parallel=ParallelConfig(data_parallel=2),
        optimizer=OptimizerConfig(lr=1e-3, clip_grad=1.0,
                                  lr_warmup_iters=2),
        train=TrainConfig(**train),
    ).validate()


def test_pretrain_end_to_end(tmp_path):
    cfg = _cfg(tmp_path)
    ds = MockDataset(cfg.model.vocab_size, cfg.train.seq_length)
    valid = MockDataset(cfg.model.vocab_size, cfg.train.seq_length, n=64,
                        seed=999)
    state = pretrain(cfg, ds, valid)
    assert int(state.iteration) == 4
    # final save happened and the tracker points at it
    assert checkpointing.read_tracker(cfg.train.save) == 4
    meta = checkpointing.load_meta(cfg.train.save)
    assert meta["consumed_samples"] == 4 * 8


def test_pretrain_resume(tmp_path):
    cfg = _cfg(tmp_path, train_iters=2, save_interval=2)
    ds = MockDataset(cfg.model.vocab_size, cfg.train.seq_length)
    pretrain(cfg, ds)
    # second run: 2 more iterations from the checkpoint
    cfg2 = _cfg(tmp_path, train_iters=4, save_interval=100,
                load=str(tmp_path / "ckpt"))
    state = pretrain(cfg2, ds)
    assert int(state.iteration) == 4
    assert checkpointing.load_meta(cfg2.train.save)["consumed_samples"] == 32


def test_skip_iters_fault_injection(tmp_path):
    cfg = _cfg(tmp_path, skip_iters=(2,), save=None)
    ds = MockDataset(cfg.model.vocab_size, cfg.train.seq_length)
    state = pretrain(cfg, ds)
    # skipped iteration still counts toward the total
    assert int(state.iteration) == 4


def test_rampup_batch_size(tmp_path):
    cfg = _cfg(tmp_path, train_iters=6, rampup_batch_size=(4, 4, 16),
               global_batch_size=8, save=None, eval_interval=1000)
    ds = MockDataset(cfg.model.vocab_size, cfg.train.seq_length)
    state = pretrain(cfg, ds)
    assert int(state.iteration) == 6


def test_exit_interval(tmp_path):
    cfg = _cfg(tmp_path, train_iters=100, exit_interval=3, save=None)
    ds = MockDataset(cfg.model.vocab_size, cfg.train.seq_length)
    state = pretrain(cfg, ds)
    assert int(state.iteration) == 3


def test_setup_with_external_params(tmp_path):
    """HF-conversion entry: params supplied externally are used as-is."""
    import jax

    from megatron_llm_tpu.models import model as model_lib

    cfg = _cfg(tmp_path, save=None)
    params = model_lib.init_params(jax.random.key(42), cfg.model)
    art = setup_train_state(cfg, params=params)
    leaves = jax.tree.leaves(art.state.params)
    assert all(bool(l.is_fully_addressable) for l in leaves)


def test_timers():
    t = Timers(log_level=1)
    t("a", log_level=0).start()
    t("a").stop()
    assert t("a").count == 1
    # above active level → null timer
    null = t("deep", log_level=2)
    null.start()
    null.stop()
    assert null.elapsed() == 0.0
    line = t.log(printer=None)
    assert "a" in line

    class W:
        def __init__(self):
            self.rows = []

        def add_scalar(self, tag, v, it):
            self.rows.append((tag, v, it))

    t("b", log_level=0).start()
    t("b").stop()
    w = W()
    t.write(w, iteration=5)
    assert any(r[0] == "timers/b" for r in w.rows)


def test_profile_window_writes_trace(tmp_path):
    """--profile_dir: a jax.profiler capture of the configured step window
    lands on disk (and an end-past-train_iters window still closes)."""
    import os

    prof = tmp_path / "prof"
    cfg = _cfg(tmp_path, train_iters=3, save=None, eval_interval=1000,
               profile_dir=str(prof), profile_step_start=2,
               profile_step_end=10)  # end past train_iters: loop-exit close
    ds = MockDataset(cfg.model.vocab_size, cfg.train.seq_length)
    state = pretrain(cfg, ds)
    assert int(state.iteration) == 3
    traces = []
    for root, _, files in os.walk(prof):
        traces += [f for f in files if "xplane" in f or "trace" in f]
    assert traces, "no profiler capture written"


def test_profile_window_not_retriggered_on_resume(tmp_path):
    """Resuming past the profile window must not write a stray trace."""
    import os

    cfg = _cfg(tmp_path, train_iters=2, save_interval=2)
    ds = MockDataset(cfg.model.vocab_size, cfg.train.seq_length)
    pretrain(cfg, ds)
    prof = tmp_path / "prof_resume"
    cfg2 = _cfg(tmp_path, train_iters=4, save_interval=100,
                load=str(tmp_path / "ckpt"), profile_dir=str(prof),
                profile_step_start=1, profile_step_end=2)  # before resume pt
    state = pretrain(cfg2, ds)
    assert int(state.iteration) == 4
    assert not prof.exists() or not any(
        f for _, _, fs in os.walk(prof) for f in fs)


def test_profile_window_with_skip_iters(tmp_path):
    """A profile window overlapping --skip_iters must still open and
    close correctly (skipped steps bypass the train step but not the
    profiler bookkeeping)."""
    import os

    prof = tmp_path / "prof_skip"
    cfg = _cfg(tmp_path, train_iters=4, save=None, eval_interval=1000,
               skip_iters=(2, 3), profile_dir=str(prof),
               profile_step_start=2, profile_step_end=3)
    ds = MockDataset(cfg.model.vocab_size, cfg.train.seq_length)
    state = pretrain(cfg, ds)
    assert int(state.iteration) == 4
    traces = [f for _, _, fs in os.walk(prof) for f in fs
              if "xplane" in f or "trace" in f]
    assert traces, "window over skipped iterations never closed/wrote"


def test_persistent_eval_iterator_advances_and_wraps(tmp_path):
    """Each eval hook must see the NEXT validation batches, not restart at
    sample 0 (reference advances one persistent valid iterator for the
    whole run, training.py:877-961); exhaustion wraps to the top."""
    from megatron_llm_tpu.training.driver import _PersistentEvalIterator

    cfg = _cfg(tmp_path, save=None)
    gbs = 8
    valid = MockDataset(cfg.model.vocab_size, cfg.train.seq_length, n=24,
                        seed=7)
    pit = _PersistentEvalIterator(cfg, valid, eod_token=None)

    b1 = next(pit.iterator(gbs))          # hook 1, batch 1
    b2 = next(pit.iterator(gbs))          # hook 2 continues, batch 2
    assert not np.array_equal(b1["tokens"], b2["tokens"])
    assert pit.consumed == 2 * gbs

    b3 = next(pit.iterator(gbs))          # batch 3 exhausts n=24
    b4 = next(pit.iterator(gbs))          # wrap: back to batch 1
    assert not np.array_equal(b3["tokens"], b1["tokens"])
    assert np.array_equal(b4["tokens"], b1["tokens"])
    assert pit.consumed == gbs  # reset on wrap, then one batch consumed


def test_persistent_eval_iterator_rebuilds_on_gbs_change(tmp_path):
    from megatron_llm_tpu.training.driver import _PersistentEvalIterator

    cfg = _cfg(tmp_path, save=None)
    valid = MockDataset(cfg.model.vocab_size, cfg.train.seq_length, n=64,
                        seed=7)
    pit = _PersistentEvalIterator(cfg, valid, eod_token=None)
    b = next(pit.iterator(8))
    assert b["tokens"].reshape(-1, b["tokens"].shape[-1]).shape[0] == 8
    b = next(pit.iterator(16))  # rampup: larger accum, position preserved
    assert b["tokens"].reshape(-1, b["tokens"].shape[-1]).shape[0] == 16
    assert pit.consumed == 8 + 16


def test_cluster_any_raises_on_degraded_collective(monkeypatch):
    """In a multi-host run a failed consensus allgather must raise, not
    silently fall back to a per-host decision (which would deadlock the
    next collective when hosts diverge)."""
    import jax

    from megatron_llm_tpu.training import driver as drv

    monkeypatch.setattr(jax, "process_count", lambda: 2)

    from jax.experimental import multihost_utils

    def boom(x):
        raise ValueError("collective transport down")

    monkeypatch.setattr(multihost_utils, "process_allgather", boom)
    with pytest.raises(RuntimeError, match="consensus allgather failed"):
        drv._cluster_any(True)


def test_cluster_any_single_process_is_local():
    from megatron_llm_tpu.training import driver as drv

    assert drv._cluster_any(True) is True
    assert drv._cluster_any(False) is False
