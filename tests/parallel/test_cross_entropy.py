"""Vocab-parallel cross entropy tests (parity: reference
tests/tensor_parallel/test_cross_entropy.py + mpu/tests/test_cross_entropy.py
— there the check is TP-sharded CE vs serial torch CE after identical
seeding; here shard_map CE vs the plain stable CE, plus grads)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from megatron_llm_tpu.parallel.cross_entropy import (
    cross_entropy,
    masked_mean_loss,
    vocab_parallel_cross_entropy_shardmap,
)


def _ref_ce(logits, targets):
    logits = np.asarray(logits, np.float64)
    m = logits.max(-1, keepdims=True)
    lse = np.log(np.exp(logits - m).sum(-1)) + m[..., 0]
    tl = np.take_along_axis(logits, targets[..., None], -1)[..., 0]
    return lse - tl


@pytest.fixture
def tp_mesh(devices):
    return Mesh(np.asarray(devices).reshape(1, 1, 1, 1, 8),
                ("dp", "pp", "cp", "ep", "tp"))


def test_cross_entropy_matches_numpy(rng):
    logits = jnp.asarray(rng.normal(size=(2, 8, 40)), jnp.float32)
    targets = jnp.asarray(rng.integers(0, 40, (2, 8)), jnp.int32)
    got = cross_entropy(logits, targets)
    np.testing.assert_allclose(got, _ref_ce(logits, targets), rtol=1e-5)


def test_padded_vocab_masking(rng):
    """Padded columns must not contribute, with or without smoothing."""
    logits = jnp.asarray(rng.normal(size=(2, 8, 40)), jnp.float32)
    targets = jnp.asarray(rng.integers(0, 32, (2, 8)), jnp.int32)
    # huge logits in padded region must be ignored
    poisoned = logits.at[..., 32:].set(100.0)
    got = cross_entropy(poisoned, targets, vocab_size=32)
    want = _ref_ce(logits[..., :32], targets)
    np.testing.assert_allclose(got, want, rtol=1e-5)

    # label smoothing over padded vocab stays finite and equals the
    # unpadded-computed value
    sm_pad = cross_entropy(poisoned, targets, label_smoothing=0.1, vocab_size=32)
    sm_ref = cross_entropy(logits[..., :32], targets, label_smoothing=0.1)
    np.testing.assert_allclose(sm_pad, sm_ref, rtol=1e-5)
    assert float(jnp.max(jnp.abs(sm_pad))) < 1e3


def test_label_smoothing_reference_formula(rng):
    """loss = (1-s)*nll - s*mean(log_probs), s = ls*K/(K-1)
    (reference cross_entropy.py:71-86)."""
    K = 16
    ls = 0.1
    logits = jnp.asarray(rng.normal(size=(4, K)), jnp.float32)
    targets = jnp.asarray(rng.integers(0, K, (4,)), jnp.int32)
    log_probs = np.asarray(jax.nn.log_softmax(logits, -1), np.float64)
    nll = -np.take_along_axis(log_probs, np.asarray(targets)[:, None], -1)[:, 0]
    s = ls * K / (K - 1)
    want = (1 - s) * nll - s * log_probs.mean(-1)
    got = cross_entropy(logits, targets, label_smoothing=ls)
    np.testing.assert_allclose(got, want, rtol=1e-5)


@pytest.mark.parametrize("smoothing,vocab_size", [(0.0, None), (0.1, 56)])
def test_shardmap_matches_plain(tp_mesh, rng, smoothing, vocab_size):
    logits = jnp.asarray(rng.normal(size=(2, 4, 64)) * 3, jnp.float32)
    targets = jnp.asarray(rng.integers(0, vocab_size or 64, (2, 4)), jnp.int32)
    sharded = jax.device_put(
        logits, NamedSharding(tp_mesh, P(None, None, "tp")))
    got = vocab_parallel_cross_entropy_shardmap(
        sharded, targets, tp_mesh, label_smoothing=smoothing,
        vocab_size=vocab_size)
    want = cross_entropy(logits, targets, label_smoothing=smoothing,
                         vocab_size=vocab_size)
    np.testing.assert_allclose(got, want, rtol=2e-5)


def test_shardmap_gradients_match(tp_mesh, rng):
    """The custom-backward parity check: d loss / d logits must agree."""
    logits = jnp.asarray(rng.normal(size=(2, 4, 64)), jnp.float32)
    targets = jnp.asarray(rng.integers(0, 64, (2, 4)), jnp.int32)

    def loss_plain(lg):
        return jnp.mean(cross_entropy(lg, targets))

    def loss_sm(lg):
        return jnp.mean(
            vocab_parallel_cross_entropy_shardmap(lg, targets, tp_mesh))

    g1 = jax.grad(loss_plain)(logits)
    sharded = jax.device_put(
        logits, NamedSharding(tp_mesh, P(None, None, "tp")))
    g2 = jax.grad(loss_sm)(sharded)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=1e-6)


def test_masked_mean_loss(rng):
    loss = jnp.asarray(rng.normal(size=(2, 8)) ** 2, jnp.float32)
    mask = jnp.zeros((2, 8)).at[:, :4].set(1.0)
    got = masked_mean_loss(loss, mask)
    np.testing.assert_allclose(got, np.asarray(loss)[:, :4].mean(), rtol=1e-6)
    # all-masked → finite zero, no NaN
    assert float(masked_mean_loss(loss, jnp.zeros((2, 8)))) == 0.0


def test_fused_linear_cross_entropy_matches_plain():
    """Blockwise fused linear+CE == plain logits CE, fwd and both grads,
    incl. padded-vocab masking and a non-divisible block size."""
    import jax

    from megatron_llm_tpu.parallel.cross_entropy import (
        fused_linear_cross_entropy,
    )

    rng = np.random.default_rng(0)
    n, h, v, v_padded = 48, 24, 90, 112
    x = jnp.asarray(rng.normal(size=(n, h)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(h, v_padded)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, v, n), jnp.int32)

    want = cross_entropy((x @ w)[None], labels[None], vocab_size=v)[0]
    got = fused_linear_cross_entropy(x, w, labels, v, 48)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)

    ref_g = jax.grad(
        lambda a, b: jnp.sum(cross_entropy((a @ b)[None], labels[None],
                                           vocab_size=v)),
        argnums=(0, 1))(x, w)
    fused_g = jax.grad(
        lambda a, b: jnp.sum(fused_linear_cross_entropy(a, b, labels, v,
                                                        48)),
        argnums=(0, 1))(x, w)
    for r, f in zip(ref_g, fused_g):
        np.testing.assert_allclose(np.asarray(f), np.asarray(r),
                                   atol=1e-5, rtol=1e-5)


def test_fused_lm_head_train_step_matches_plain():
    """A train step with cfg.model.fused_lm_head gives the same loss."""
    import jax

    from megatron_llm_tpu.config import (
        OptimizerConfig, ParallelConfig, RuntimeConfig, TrainConfig,
        tiny_config,
    )
    from megatron_llm_tpu.models import model as model_lib
    from megatron_llm_tpu.training.step import (
        init_train_state, make_train_step,
    )

    gen = np.random.default_rng(3)
    tokens = gen.integers(0, 64, (1, 2, 32))
    batch = {
        "tokens": jnp.asarray(tokens, jnp.int32),
        "labels": jnp.asarray(np.roll(tokens, -1, -1), jnp.int32),
        "loss_mask": jnp.ones((1, 2, 32), jnp.float32),
    }

    def run(fused):
        cfg = RuntimeConfig(
            model=tiny_config(fused_lm_head=fused),
            parallel=ParallelConfig(),
            optimizer=OptimizerConfig(lr=1e-3),
            train=TrainConfig(train_iters=1, micro_batch_size=2,
                              global_batch_size=2, seq_length=32,
                              save=None),
        ).validate()
        params = model_lib.init_params(jax.random.key(0), cfg.model)
        state = init_train_state(cfg, params)
        step = make_train_step(cfg)
        _, m = step(state, batch, None)
        return float(m["loss"]), float(m["grad_norm"])

    loss_f, gn_f = run(True)
    loss_p, gn_p = run(False)
    np.testing.assert_allclose(loss_f, loss_p, rtol=1e-5)
    np.testing.assert_allclose(gn_f, gn_p, rtol=1e-4)
