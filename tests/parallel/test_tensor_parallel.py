"""Tensor-parallel (pp=1) parity: sharded vs unsharded exactness.

Round-1 VERDICT weak #4: TP parity evidence was only tp=2 inside the
pipeline tests.  Here the Column/Row/Vocab PartitionSpec layout
(models/sharding.py) is checked directly at tp∈{4,8}, with and without
sequence parallelism, for loss AND grads against the single-device model —
the GSPMD analogue of the reference's mpu layer tests
(megatron/mpu/tests/test_layers.py:16-40).  Plus ZeRO-1 (distributed
optimizer) on/off state equivalence over real train steps.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from megatron_llm_tpu.config import (
    OptimizerConfig,
    ParallelConfig,
    RuntimeConfig,
    TrainConfig,
    tiny_config,
)
from megatron_llm_tpu.models import model as model_lib
from megatron_llm_tpu.models import sharding as shard_lib
from megatron_llm_tpu.parallel import mesh as mesh_lib
from megatron_llm_tpu.training import optimizer as opt_lib
from megatron_llm_tpu.training.step import (
    TrainState,
    compute_loss,
    guard_spec,
    init_train_state,
    make_train_step,
)


def _model_cfg(tp):
    return tiny_config(
        num_layers=2,
        hidden_size=64,
        num_attention_heads=8,
        num_kv_heads=8,
        ffn_hidden_size=128,
        vocab_size=256,
        make_vocab_size_divisible_by=8 * tp,
        params_dtype="float32",
        recompute="none",
        seq_length=32,
        max_position_embeddings=32,
    )


def _runtime(cfg, parallel):
    return RuntimeConfig(model=cfg, parallel=parallel,
                         optimizer=OptimizerConfig(),
                         train=TrainConfig(seq_length=cfg.seq_length)
                         ).validate()


def _batch(cfg, b=4, seed=3):
    g = np.random.default_rng(seed)
    s = cfg.seq_length
    return {
        "tokens": jnp.asarray(
            g.integers(0, cfg.vocab_size, (b, s)), jnp.int32),
        "labels": jnp.asarray(
            g.integers(0, cfg.vocab_size, (b, s)), jnp.int32),
        "loss_mask": jnp.ones((b, s), jnp.float32),
    }


@pytest.mark.parametrize("tp,sequence_parallel", [
    (4, False), (4, True), (8, False), (8, True),
])
def test_tp_loss_and_grads_match_unsharded(tp, sequence_parallel):
    cfg = _model_cfg(tp)
    parallel = ParallelConfig(tensor_parallel=tp,
                              sequence_parallel=sequence_parallel)
    runtime = _runtime(cfg, parallel)
    if sequence_parallel:
        assert runtime.model.sequence_parallel_axis == "tp"
    mesh = mesh_lib.build_mesh(parallel)

    params = model_lib.init_params(jax.random.key(0), cfg, tp=tp)
    batch = _batch(cfg)

    # Single-device reference (no mesh, replicated everything).
    ref_runtime = _runtime(cfg, ParallelConfig())

    def ref_loss(p):
        return compute_loss(ref_runtime, p, batch)

    ref_l, ref_g = jax.value_and_grad(ref_loss)(params)

    # Sharded run under the tp mesh.
    specs = shard_lib.param_specs(cfg, parallel)
    sharded = shard_lib.shard_params(params, specs, mesh)

    def tp_loss(p):
        return compute_loss(runtime, p, batch)

    with mesh_lib.use_mesh(mesh):
        tp_l, tp_g = jax.jit(jax.value_and_grad(tp_loss))(sharded)

    np.testing.assert_allclose(np.asarray(tp_l), np.asarray(ref_l),
                               rtol=1e-5, atol=1e-6)
    flat_ref = jax.tree.leaves_with_path(ref_g)
    flat_tp = dict(jax.tree.leaves_with_path(tp_g))
    assert len(flat_ref) == len(flat_tp)
    for path, ref in flat_ref:
        got = flat_tp[path]
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=5e-5, atol=1e-5,
            err_msg=f"tp={tp} sp={sequence_parallel} grad mismatch at "
                    f"{jax.tree_util.keystr(path)}")


def test_sequence_parallel_actually_shards_seq():
    """The SP constraint must be visible in the compiled sharding: norm-
    region activations carry the seq dim over 'tp' (not just the flag)."""
    tp = 4
    cfg = _model_cfg(tp)
    parallel = ParallelConfig(tensor_parallel=tp, sequence_parallel=True)
    runtime = _runtime(cfg, parallel)
    mesh = mesh_lib.build_mesh(parallel)
    params = model_lib.init_params(jax.random.key(0), cfg, tp=tp)
    specs = shard_lib.param_specs(cfg, parallel)
    sharded = shard_lib.shard_params(params, specs, mesh)
    batch = _batch(cfg)

    with mesh_lib.use_mesh(mesh):
        lowered = jax.jit(
            lambda p: compute_loss(runtime, p, batch)).lower(sharded)
    # The residual-stream constraint lowers to a shardy annotation with the
    # seq dim on "tp" and batch/hidden left open: [{?}, {"tp"}, {?}].
    hlo = lowered.as_text()
    assert 'sharding_constraint' in hlo, "no sharding constraint emitted"
    assert '[{?}, {"tp"}, {?}]' in hlo, (
        "no seq-over-tp residual constraint found — sequence parallelism "
        "not applied")


@pytest.mark.parametrize("tp", [2])
def test_zero1_state_equivalence(tp):
    """ZeRO-1 (opt state sharded over dp) must produce the same params and
    optimizer moments as the replicated optimizer, step for step
    (reference contract: distrib_optimizer.py is a memory layout change,
    not an algorithm change)."""
    dp = 4
    cfg = _model_cfg(tp)

    def run(use_dist_opt):
        parallel = ParallelConfig(data_parallel=dp, tensor_parallel=tp,
                                  use_distributed_optimizer=use_dist_opt)
        runtime = RuntimeConfig(
            model=cfg, parallel=parallel,
            optimizer=OptimizerConfig(lr=1e-2, clip_grad=1.0),
            train=TrainConfig(train_iters=3, seq_length=cfg.seq_length,
                              micro_batch_size=2,
                              global_batch_size=2 * 2 * dp),
        ).validate()
        mesh = mesh_lib.build_mesh(parallel)
        params = model_lib.init_params(jax.random.key(1), cfg, tp=tp)
        pspecs = shard_lib.param_specs(cfg, parallel)
        params = shard_lib.shard_params(params, pspecs, mesh)
        state = init_train_state(runtime, params)
        ospecs = opt_lib.opt_state_specs(pspecs, params, parallel, state.opt)
        state_spec = TrainState(params=pspecs, opt=ospecs,
                                iteration=P(), skipped=P(),
                                guard=guard_spec())
        state_sharding = jax.tree.map(
            lambda s: NamedSharding(mesh, s), state_spec,
            is_leaf=lambda x: isinstance(x, P))
        state = jax.tree.map(lambda x, s: jax.device_put(x, s),
                             state, state_sharding)
        batch_sharding = NamedSharding(mesh, P(None, "dp"))

        g = np.random.default_rng(11)
        shape = (2, 2 * dp, cfg.seq_length)  # [accum, micro*dp, s]
        with mesh_lib.use_mesh(mesh):
            step = make_train_step(
                runtime, mesh, state_sharding,
                {"tokens": batch_sharding, "labels": batch_sharding,
                 "loss_mask": batch_sharding})
            for i in range(3):
                toks = g.integers(0, cfg.vocab_size, shape)
                batch = {
                    "tokens": jnp.asarray(toks, jnp.int32),
                    "labels": jnp.asarray(np.roll(toks, -1, -1), jnp.int32),
                    "loss_mask": jnp.ones(shape, jnp.float32),
                }
                batch = jax.tree.map(
                    lambda x: jax.device_put(x, batch_sharding), batch)
                state, metrics = step(state, batch, None)
        return jax.device_get((state.params, state.opt.mu, state.opt.nu,
                               metrics["loss"]))

    p_rep, mu_rep, nu_rep, loss_rep = run(False)
    p_z1, mu_z1, nu_z1, loss_z1 = run(True)

    np.testing.assert_allclose(loss_z1, loss_rep, rtol=1e-6)
    for name, a, b in (("params", p_rep, p_z1), ("mu", mu_rep, mu_z1),
                       ("nu", nu_rep, nu_z1)):
        for (path, x), (_, y) in zip(jax.tree.leaves_with_path(a),
                                     jax.tree.leaves_with_path(b)):
            # atol covers f32 rounding from the dp-sharded vs replicated
            # Adam update orders (observed max |Δ| ≈ 2e-6 over 3 steps)
            np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), rtol=1e-4, atol=1e-5,
                err_msg=f"ZeRO-1 {name} mismatch at "
                        f"{jax.tree_util.keystr(path)}")
