"""Ring attention (context parallelism) vs the unsharded reference path.

Validates that sharding the sequence over the cp mesh axis and rotating
K/V blocks with ppermute reproduces exact softmax attention — forward and
backward — including GQA grouping and packed-sequence segment masks.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from megatron_llm_tpu.ops.attention import dot_product_attention
from megatron_llm_tpu.parallel.ring_attention import ring_attention
from megatron_llm_tpu.parallel import mesh as mesh_lib


def cp_mesh(devices, cp):
    n = len(devices)
    devs = np.asarray(devices).reshape(n // cp, 1, 1, cp, 1, 1, 1)
    return Mesh(devs, mesh_lib.AXIS_ORDER)


def make_qkv(rng, b=2, s=32, nq=4, nkv=2, d=8):
    q = jnp.asarray(rng.normal(size=(b, s, nq, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, s, nkv, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, s, nkv, d)), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("cp", [2, 4, 8])
def test_ring_matches_dot_causal(devices, rng, cp):
    mesh = cp_mesh(devices, cp)
    q, k, v = make_qkv(rng)
    want = dot_product_attention(q, k, v, causal=True)

    spec = NamedSharding(mesh, P(None, "cp"))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    got = jax.jit(
        lambda a, b_, c: ring_attention(a, b_, c, mesh=mesh, causal=True)
    )(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_ring_non_causal(devices, rng):
    mesh = cp_mesh(devices, 4)
    q, k, v = make_qkv(rng)
    want = dot_product_attention(q, k, v, causal=False)
    got = jax.jit(
        lambda a, b_, c: ring_attention(a, b_, c, mesh=mesh, causal=False)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_ring_segment_ids(devices, rng):
    mesh = cp_mesh(devices, 4)
    b, s = 2, 32
    q, k, v = make_qkv(rng, b=b, s=s)
    # two packed sequences per row, boundary inside a shard and across shards
    seg = jnp.asarray(
        np.stack([np.r_[[0] * 10, [1] * 22], np.r_[[0] * 20, [1] * 12]]),
        jnp.int32,
    )
    want = dot_product_attention(q, k, v, causal=True, segment_ids=seg)
    got = jax.jit(
        lambda a, b_, c, s_: ring_attention(a, b_, c, mesh=mesh, causal=True,
                                            segment_ids=s_)
    )(q, k, v, seg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_ring_gradients_match(devices, rng):
    mesh = cp_mesh(devices, 4)
    q, k, v = make_qkv(rng, s=16)
    tgt = jnp.asarray(rng.normal(size=q.shape), jnp.float32)

    def loss_ref(q_, k_, v_):
        return jnp.sum((dot_product_attention(q_, k_, v_, causal=True) - tgt) ** 2)

    def loss_ring(q_, k_, v_):
        return jnp.sum((ring_attention(q_, k_, v_, mesh=mesh, causal=True) - tgt) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    for a, b_ in zip(g_ref, g_ring):
        np.testing.assert_allclose(np.asarray(b_), np.asarray(a),
                                   atol=1e-4, rtol=1e-4)


def test_model_forward_with_cp(devices):
    """Full decoder forward: cp-sharded model == unsharded model."""
    import dataclasses

    from megatron_llm_tpu.config import llama2_config
    from megatron_llm_tpu.models import model as model_lib

    cfg = llama2_config(
        "7b", hidden_size=64, num_layers=2, num_attention_heads=4,
        num_kv_heads=2, ffn_hidden_size=128, vocab_size=256,
        seq_length=32, max_position_embeddings=32,
        params_dtype="float32", attention_impl="dot", recompute="none",
    )
    params = model_lib.init_params(jax.random.key(0), cfg)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, (2, 32)), jnp.int32)
    want = model_lib.forward(cfg, params, tokens)

    mesh = cp_mesh(devices, 4)
    cfg_cp = dataclasses.replace(cfg, context_parallel_axis="cp")
    tok_sharded = jax.device_put(tokens, NamedSharding(mesh, P(None, "cp")))
    with mesh_lib.use_mesh(mesh):
        got = jax.jit(
            lambda p, t: model_lib.forward(cfg_cp, p, t)
        )(params, tok_sharded)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-4, rtol=2e-4)


def test_train_step_with_context_parallelism():
    """Driver-level: ParallelConfig.context_parallel=2 wires the ring path
    (via RuntimeConfig.validate) and the train-step loss matches cp=1."""
    from megatron_llm_tpu.config import (
        OptimizerConfig, ParallelConfig, RuntimeConfig, TrainConfig,
        tiny_config,
    )
    from megatron_llm_tpu.models import model as model_lib
    from megatron_llm_tpu.training.driver import setup_train_state

    gen = np.random.default_rng(7)
    tokens = gen.integers(0, 64, (1, 4, 32))
    batch = {
        "tokens": jnp.asarray(tokens, jnp.int32),
        "labels": jnp.asarray(np.roll(tokens, -1, axis=-1), jnp.int32),
        "loss_mask": jnp.ones((1, 4, 32), jnp.float32),
    }

    def run(cp, pp=1, dp=2, gbs=4, b=None):
        cfg = RuntimeConfig(
            model=tiny_config(),
            parallel=ParallelConfig(
                data_parallel=dp, context_parallel=cp, pipeline_parallel=pp,
                num_microbatches=(gbs // (2 * dp)) if pp > 1 else 1),
            optimizer=OptimizerConfig(lr=1e-3, clip_grad=1.0),
            train=TrainConfig(
                train_iters=2, micro_batch_size=2, global_batch_size=gbs,
                seq_length=32, save=None,
            ),
        ).validate()
        if cp > 1:
            assert cfg.model.context_parallel_axis == "cp"
        params = model_lib.init_params(jax.random.key(3), cfg.model)
        art = setup_train_state(cfg, params=params)
        if b is None:
            b = batch
            if pp > 1:
                # pipeline consumes [M, mb, ...] microbatches
                b = jax.tree.map(
                    lambda x: x.reshape(2, 2, *x.shape[2:]), batch)
        _, metrics = art.step_fn(art.state, b, None)
        return float(metrics["loss"])

    loss_ref = run(1)
    loss_cp = run(2)
    assert np.isfinite(loss_cp)
    np.testing.assert_allclose(loss_cp, loss_ref, rtol=1e-4, atol=1e-4)
    # pipeline (pp=2) combined with ring attention (cp=2)
    loss_pp_cp = run(2, pp=2, dp=1)
    np.testing.assert_allclose(loss_pp_cp, loss_ref, rtol=1e-3, atol=1e-3)
    # the full manual-axis triple: dp AND cp AND pp all manual inside the
    # pipeline shard_map (dp became manual in round 3 — the XLA
    # partitioner-crash fix).  Self-consistent config: gbs 8 = mb 2 ×
    # dp 2 × M 2; the 8-sample batch duplicates the reference data so
    # the mean loss is unchanged.
    big = jax.tree.map(
        lambda x: jnp.concatenate([x, x], axis=1
                                  ).reshape(2, 4, *x.shape[2:]), batch)
    loss_triple = run(2, pp=2, dp=2, gbs=8, b=big)
    np.testing.assert_allclose(loss_triple, loss_ref, rtol=1e-3, atol=1e-3)


def test_train_step_with_zigzag_layout():
    """context_parallel_layout='zigzag' reproduces the cp=1 loss (the batch
    permutation + global position ids + balanced ring compose exactly)."""
    from megatron_llm_tpu.config import (
        OptimizerConfig, ParallelConfig, RuntimeConfig, TrainConfig,
        tiny_config,
    )
    from megatron_llm_tpu.models import model as model_lib
    from megatron_llm_tpu.training.driver import setup_train_state

    gen = np.random.default_rng(11)
    tokens = gen.integers(0, 64, (1, 4, 32))
    batch = {
        "tokens": jnp.asarray(tokens, jnp.int32),
        "labels": jnp.asarray(np.roll(tokens, -1, axis=-1), jnp.int32),
        "loss_mask": jnp.ones((1, 4, 32), jnp.float32),
    }

    def run(cp, layout="contiguous"):
        cfg = RuntimeConfig(
            model=tiny_config(),
            parallel=ParallelConfig(data_parallel=2, context_parallel=cp,
                                    context_parallel_layout=layout),
            optimizer=OptimizerConfig(lr=1e-3, clip_grad=1.0),
            train=TrainConfig(
                train_iters=2, micro_batch_size=2, global_batch_size=4,
                seq_length=32, save=None,
            ),
        ).validate()
        if layout == "zigzag":
            assert cfg.model.context_parallel_zigzag
        params = model_lib.init_params(jax.random.key(3), cfg.model)
        art = setup_train_state(cfg, params=params)
        _, metrics = art.step_fn(art.state, batch, None)
        return float(metrics["loss"]), float(metrics["grad_norm"])

    loss_ref, gn_ref = run(1)
    loss_zz, gn_zz = run(4, "zigzag")
    np.testing.assert_allclose(loss_zz, loss_ref, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(gn_zz, gn_ref, rtol=1e-3, atol=1e-4)


def test_zigzag_indices_roundtrip():
    from megatron_llm_tpu.parallel.ring_attention import (
        inverse_zigzag_indices, zigzag_indices,
    )

    for s, cp in [(32, 4), (64, 8), (48, 2)]:
        pi = zigzag_indices(s, cp)
        inv = inverse_zigzag_indices(s, cp)
        x = np.arange(s)
        np.testing.assert_array_equal(x[pi][inv], x)
        # shard r holds chunks (r, 2cp-1-r)
        c = s // (2 * cp)
        for r in range(cp):
            shard = pi[r * 2 * c:(r + 1) * 2 * c]
            assert (shard[:c] == np.arange(r * c, (r + 1) * c)).all()
            hi = 2 * cp - 1 - r
            assert (shard[c:] == np.arange(hi * c, (hi + 1) * c)).all()


@pytest.mark.parametrize("cp", [2, 4])
def test_zigzag_ring_matches_dot_causal(devices, rng, cp):
    from megatron_llm_tpu.parallel.ring_attention import (
        inverse_zigzag_indices, ring_attention_zigzag, zigzag_indices,
    )

    mesh = cp_mesh(devices, cp)
    q, k, v = make_qkv(rng)
    want = dot_product_attention(q, k, v, causal=True)

    s = q.shape[1]
    pi = zigzag_indices(s, cp)
    inv = inverse_zigzag_indices(s, cp)
    spec = NamedSharding(mesh, P(None, "cp"))
    qz, kz, vz = (jax.device_put(x[:, pi], spec) for x in (q, k, v))
    got_z = jax.jit(
        lambda a, b_, c: ring_attention_zigzag(a, b_, c, mesh=mesh)
    )(qz, kz, vz)
    got = np.asarray(got_z)[:, inv]
    np.testing.assert_allclose(got, np.asarray(want), atol=1e-5, rtol=1e-5)


def test_zigzag_ring_gradients_match(devices, rng):
    from megatron_llm_tpu.parallel.ring_attention import (
        inverse_zigzag_indices, ring_attention_zigzag, zigzag_indices,
    )

    cp = 4
    mesh = cp_mesh(devices, cp)
    q, k, v = make_qkv(rng, s=32)
    s = q.shape[1]
    pi = zigzag_indices(s, cp)
    inv = inverse_zigzag_indices(s, cp)
    tgt = jnp.asarray(rng.normal(size=q.shape), jnp.float32)

    def loss_ref(q_, k_, v_):
        return jnp.sum((dot_product_attention(q_, k_, v_, causal=True)
                        - tgt) ** 2)

    def loss_zz(q_, k_, v_):
        out = ring_attention_zigzag(q_[:, pi], k_[:, pi], v_[:, pi],
                                    mesh=mesh)[:, inv]
        return jnp.sum((out - tgt) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_zz = jax.jit(jax.grad(loss_zz, argnums=(0, 1, 2)))(q, k, v)
    for a, b_ in zip(g_ref, g_zz):
        np.testing.assert_allclose(np.asarray(b_), np.asarray(a),
                                   atol=1e-4, rtol=1e-4)


def test_zigzag_ring_segment_ids(devices, rng):
    from megatron_llm_tpu.parallel.ring_attention import (
        inverse_zigzag_indices, ring_attention_zigzag, zigzag_indices,
    )

    cp = 4
    mesh = cp_mesh(devices, cp)
    b, s = 2, 32
    q, k, v = make_qkv(rng, b=b, s=s)
    seg = jnp.asarray(
        np.stack([np.r_[[0] * 10, [1] * 22], np.r_[[0] * 20, [1] * 12]]),
        jnp.int32)
    want = dot_product_attention(q, k, v, causal=True, segment_ids=seg)
    pi = zigzag_indices(s, cp)
    inv = inverse_zigzag_indices(s, cp)
    got = jax.jit(
        lambda a, b_, c, s_: ring_attention_zigzag(a, b_, c, mesh=mesh,
                                                   segment_ids=s_)
    )(q[:, pi], k[:, pi], v[:, pi], seg[:, pi])
    np.testing.assert_allclose(np.asarray(got)[:, inv], np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_eval_step_with_zigzag_layout():
    """Regression: the eval path must apply the same zigzag permutation as
    the train loss (natural-order eval batches were silently wrong)."""
    from megatron_llm_tpu.config import (
        OptimizerConfig, ParallelConfig, RuntimeConfig, TrainConfig,
        tiny_config,
    )
    from megatron_llm_tpu.models import model as model_lib
    from megatron_llm_tpu.parallel import mesh as mesh_lib2
    from megatron_llm_tpu.training.driver import make_eval_step

    gen = np.random.default_rng(21)
    tokens = gen.integers(0, 64, (4, 32))
    batch = {
        "tokens": jnp.asarray(tokens, jnp.int32),
        "labels": jnp.asarray(np.roll(tokens, -1, axis=-1), jnp.int32),
        "loss_mask": jnp.ones((4, 32), jnp.float32),
    }

    def run(cp, layout="contiguous"):
        cfg = RuntimeConfig(
            model=tiny_config(),
            parallel=ParallelConfig(context_parallel=cp,
                                    context_parallel_layout=layout),
            optimizer=OptimizerConfig(),
            train=TrainConfig(train_iters=1, micro_batch_size=4,
                              global_batch_size=4, seq_length=32,
                              save=None),
        ).validate()
        params = model_lib.init_params(jax.random.key(3), cfg.model)
        mesh = mesh_lib2.build_mesh(cfg.parallel)
        step = make_eval_step(cfg, (), mesh)
        with mesh_lib2.use_mesh(mesh):
            out = step(params, batch)
        return float(out["lm_loss"])

    ref = run(1)
    zz = run(4, "zigzag")
    np.testing.assert_allclose(zz, ref, rtol=1e-5, atol=1e-5)
