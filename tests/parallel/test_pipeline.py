"""Pipeline-parallel schedule correctness vs the unpipelined reference.

The reference validates its schedules only implicitly through end-to-end
runs on real GPUs; here the ppermute pipeline is checked exactly against
the single-device stack on the hermetic 8-device CPU mesh (SURVEY.md §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from megatron_llm_tpu.config import (
    OptimizerConfig,
    ParallelConfig,
    RuntimeConfig,
    TrainConfig,
    tiny_config,
)
from megatron_llm_tpu.models import model as model_lib
from megatron_llm_tpu.models import sharding as shard_lib
from megatron_llm_tpu.models.transformer import AttnSideInputs, rope_tables
from megatron_llm_tpu.parallel import mesh as mesh_lib
from megatron_llm_tpu.parallel import pipeline as pipe
from megatron_llm_tpu.parallel.cross_entropy import (
    cross_entropy,
    masked_mean_loss,
)
from megatron_llm_tpu.ops.norms import norm_apply


def _cfg(num_layers=4):
    return tiny_config(
        num_layers=num_layers,
        params_dtype="float32",
        recompute="none",
        seq_length=32,
        max_position_embeddings=32,
    )


def _batch(cfg, M, mb, seed=0):
    g = np.random.default_rng(seed)
    s = cfg.seq_length
    tokens = jnp.asarray(
        g.integers(0, cfg.vocab_size, (M, mb, s)), jnp.int32)
    labels = jnp.asarray(
        g.integers(0, cfg.vocab_size, (M, mb, s)), jnp.int32)
    mask = jnp.ones((M, mb, s), jnp.float32)
    return {"tokens": tokens, "labels": labels, "loss_mask": mask}


def _reference_loss(cfg, params, batch):
    """Unpipelined: mean over microbatches of masked-mean CE."""
    rope = rope_tables(cfg)

    def one(m):
        logits = model_lib.forward(cfg, params, batch["tokens"][m],
                                   rope=rope)
        per_token = cross_entropy(logits, batch["labels"][m],
                                  vocab_size=cfg.vocab_size)
        return masked_mean_loss(per_token, batch["loss_mask"][m])

    M = batch["tokens"].shape[0]
    return jnp.mean(jax.vmap(one)(jnp.arange(M)))


@pytest.mark.parametrize(
    "dp,pp,tp,vpp,M",
    [
        (1, 2, 1, 1, 3),
        (1, 4, 1, 1, 4),
        (2, 2, 2, 1, 4),
        (1, 2, 1, 2, 4),   # interleaved (tight): 2 virtual chunks per stage
        (1, 4, 1, 2, 4),   # interleaved (tight) at pp=4 (16 layers)
        (1, 2, 1, 2, 5),   # interleaved legacy order (M % pp != 0)
        (1, 2, 1, 3, 6),   # tight at vpp=3, 3 microbatch groups (12 layers)
    ],
)
def test_pipeline_matches_reference(dp, pp, tp, vpp, M):
    num_layers = pp * vpp * 2  # 2 layers per chunk
    cfg = _cfg(num_layers=num_layers)
    parallel = ParallelConfig(
        data_parallel=dp, pipeline_parallel=pp, tensor_parallel=tp,
        virtual_pipeline_stages=vpp, num_microbatches=M,
    )
    mesh = mesh_lib.build_mesh(parallel)

    params = model_lib.init_params(jax.random.key(0), cfg)
    batch = _batch(cfg, M, mb=2)

    ref_loss = _reference_loss(cfg, params, batch)
    ref_grads = jax.grad(
        lambda p: _reference_loss(cfg, p, batch))(params)

    # Pipeline layout + placement
    p_params = pipe.to_pipeline_params(params, parallel)
    specs = shard_lib.param_specs(cfg, parallel)
    p_specs = pipe.pipeline_param_specs(specs, parallel)
    p_params = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        p_params, p_specs, is_leaf=lambda v: isinstance(v, P))

    runtime = RuntimeConfig(model=cfg, parallel=parallel,
                            optimizer=OptimizerConfig(),
                            train=TrainConfig(seq_length=cfg.seq_length))

    @jax.jit
    def loss_fn(p, b):
        return pipe.pipeline_loss(runtime, p, b, mesh=mesh)

    with mesh_lib.use_mesh(mesh):
        pl_loss = loss_fn(p_params, batch)
        pl_grads = jax.jit(jax.grad(
            lambda p: pipe.pipeline_loss(runtime, p, batch, mesh=mesh)
        ))(p_params)

    np.testing.assert_allclose(np.asarray(pl_loss), np.asarray(ref_loss),
                               rtol=2e-5, atol=2e-5)

    # Gradients: restack the staged layer grads and compare the full tree.
    pl_grads = pipe.from_pipeline_params(pl_grads, parallel)
    flat_ref = jax.tree.leaves_with_path(ref_grads)
    flat_pl = dict(jax.tree.leaves_with_path(pl_grads))
    assert len(flat_ref) == len(flat_pl)
    for path, ref in flat_ref:
        got = flat_pl[path]
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=5e-4, atol=5e-5,
            err_msg=f"grad mismatch at {jax.tree_util.keystr(path)}",
        )


def test_stage_layout_roundtrip():
    cfg = _cfg(num_layers=8)
    params = model_lib.init_params(jax.random.key(1), cfg)
    parallel = ParallelConfig(pipeline_parallel=2,
                              virtual_pipeline_stages=2)
    staged = pipe.to_pipeline_params(params, parallel)
    back = pipe.from_pipeline_params(staged, parallel)
    for (pa, a), (pb, b) in zip(
        jax.tree.leaves_with_path(params), jax.tree.leaves_with_path(back)
    ):
        assert pa == pb
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_interleaved_layer_assignment():
    """Chunk v on stage s must hold global layers (v*pp+s)*lpc.. — the
    reference interleaved assignment (megatron/model/transformer.py:1015-60).
    """
    L, pp, vpp = 8, 2, 2
    stack = jnp.arange(L)  # pretend each layer is its own index
    staged = pipe.to_stage_layers(stack, pp, vpp)
    assert staged.shape == (vpp, pp, L // (pp * vpp))
    # chunk 0 stage 0 → layers 0,1 ; chunk 0 stage 1 → 2,3
    # chunk 1 stage 0 → layers 4,5 ; chunk 1 stage 1 → 6,7
    np.testing.assert_array_equal(np.asarray(staged[0, 0]), [0, 1])
    np.testing.assert_array_equal(np.asarray(staged[0, 1]), [2, 3])
    np.testing.assert_array_equal(np.asarray(staged[1, 0]), [4, 5])
    np.testing.assert_array_equal(np.asarray(staged[1, 1]), [6, 7])


def test_falcon_style_pipeline_matches_reference():
    """BASELINE config 3 shape: MQA (kv=1) + parallel attention +
    parallel LayerNorm through the pipelined schedule (tp=2, pp=2)."""
    cfg = tiny_config(
        num_layers=4,
        num_kv_heads=1,           # MQA
        norm_type="layernorm",
        activation="gelu",
        parallel_attn=True,
        parallel_layernorm=True,  # Falcon-40B style
        use_bias=False,
        qkv_bias=True,            # Falcon-7B attention bias
        tie_embed_logits=True,
        params_dtype="float32",
        recompute="none",
        seq_length=32,
        max_position_embeddings=32,
    )
    M = 3
    parallel = ParallelConfig(pipeline_parallel=2, tensor_parallel=2,
                              num_microbatches=M)
    mesh = mesh_lib.build_mesh(parallel)

    params = model_lib.init_params(jax.random.key(1), cfg)
    batch = _batch(cfg, M, mb=2, seed=5)

    ref_loss = _reference_loss(cfg, params, batch)
    p_params = pipe.to_pipeline_params(params, parallel)
    specs = shard_lib.param_specs(cfg, parallel)
    p_specs = pipe.pipeline_param_specs(specs, parallel)
    p_params = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        p_params, p_specs, is_leaf=lambda v: isinstance(v, P))

    runtime = RuntimeConfig(model=cfg, parallel=parallel,
                            optimizer=OptimizerConfig(),
                            train=TrainConfig(seq_length=cfg.seq_length))
    with mesh_lib.use_mesh(mesh):
        pl_loss = jax.jit(
            lambda p, b: pipe.pipeline_loss(runtime, p, b, mesh=mesh)
        )(p_params, batch)
    np.testing.assert_allclose(np.asarray(pl_loss), np.asarray(ref_loss),
                               rtol=2e-5, atol=2e-5)


def test_pipeline_with_flash_kernel_matches_reference():
    """The Pallas flash kernel must compose with the manual-pp shard_map
    (interpret mode on CPU): loss parity vs the unpipelined dot-attention
    reference at pp=2."""
    cfg = tiny_config(
        num_layers=4,
        params_dtype="float32",
        recompute="none",
        attention_impl="flash",
        seq_length=32,
        max_position_embeddings=32,
    )
    M = 3
    parallel = ParallelConfig(pipeline_parallel=2, num_microbatches=M)
    mesh = mesh_lib.build_mesh(parallel)
    params = model_lib.init_params(jax.random.key(2), cfg)
    batch = _batch(cfg, M, mb=2, seed=9)

    # reference runs DOT attention so the kernel's numerics are actually
    # under test, not cancelled out
    import dataclasses

    ref_loss = _reference_loss(
        dataclasses.replace(cfg, attention_impl="dot"), params, batch)

    p_params = pipe.to_pipeline_params(params, parallel)
    specs = shard_lib.param_specs(cfg, parallel)
    p_specs = pipe.pipeline_param_specs(specs, parallel)
    p_params = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        p_params, p_specs, is_leaf=lambda v: isinstance(v, P))
    runtime = RuntimeConfig(model=cfg, parallel=parallel,
                            optimizer=OptimizerConfig(),
                            train=TrainConfig(seq_length=cfg.seq_length))
    with mesh_lib.use_mesh(mesh):
        pl_loss = jax.jit(
            lambda p, b: pipe.pipeline_loss(runtime, p, b, mesh=mesh)
        )(p_params, batch)
    # flash runs fp32 inside; interpret-mode kernel vs einsum ≈ 1e-5
    np.testing.assert_allclose(np.asarray(pl_loss), np.asarray(ref_loss),
                               rtol=5e-5, atol=5e-5)


@pytest.mark.parametrize(
    "M,W,vpp",
    [(6, 3, 1), (5, 2, 1),       # even and ragged windows, plain 1F1B
     (4, 3, 2),                  # windowed INTERLEAVED (tight schedule)
     (4, 2, 2)],                 # interleaved + ragged (T=9, 1 padding tick)
)
def test_windowed_remat_matches_unwindowed(M, W, vpp):
    """pipeline_remat_window must change memory, not math: loss and every
    grad identical to the plain schedule (incl. ragged T % W padding
    ticks, which must be true no-ops) — for both plain 1F1B and the tight
    interleaved schedule (vpp > 1, M % pp == 0)."""
    pp = 2
    cfg = _cfg(num_layers=4 * vpp)
    base = ParallelConfig(pipeline_parallel=pp, num_microbatches=M,
                          virtual_pipeline_stages=vpp)
    windowed = ParallelConfig(pipeline_parallel=pp, num_microbatches=M,
                              virtual_pipeline_stages=vpp,
                              pipeline_remat_window=W).validate()
    mesh = mesh_lib.build_mesh(base)

    params = model_lib.init_params(jax.random.key(3), cfg)
    batch = _batch(cfg, M, mb=2, seed=11)
    p_params = pipe.to_pipeline_params(params, base)
    specs = shard_lib.param_specs(cfg, base)
    p_specs = pipe.pipeline_param_specs(specs, base)
    p_params = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        p_params, p_specs, is_leaf=lambda v: isinstance(v, P))

    def runtime(par):
        return RuntimeConfig(model=cfg, parallel=par,
                             optimizer=OptimizerConfig(),
                             train=TrainConfig(seq_length=cfg.seq_length))

    with mesh_lib.use_mesh(mesh):
        loss_plain, grads_plain = jax.jit(jax.value_and_grad(
            lambda p: pipe.pipeline_loss(runtime(base), p, batch, mesh=mesh)
        ))(p_params)
        loss_win, grads_win = jax.jit(jax.value_and_grad(
            lambda p: pipe.pipeline_loss(runtime(windowed), p, batch,
                                         mesh=mesh)
        ))(p_params)

    np.testing.assert_allclose(np.asarray(loss_win), np.asarray(loss_plain),
                               rtol=1e-6, atol=1e-6)
    for (path, a), (_, b) in zip(
        jax.tree.leaves_with_path(grads_plain),
        jax.tree.leaves_with_path(grads_win),
    ):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=1e-5, atol=1e-6,
            err_msg=f"windowed grad mismatch at {jax.tree_util.keystr(path)}")


def test_window_with_vpp_requires_divisible_microbatches():
    # tight schedule (M % pp == 0): allowed
    ParallelConfig(pipeline_parallel=2, virtual_pipeline_stages=2,
                   num_microbatches=4, pipeline_remat_window=4).validate()
    # legacy order would re-save the circular buffer per window: rejected
    with pytest.raises(AssertionError):
        ParallelConfig(pipeline_parallel=2, virtual_pipeline_stages=2,
                       num_microbatches=5, pipeline_remat_window=4).validate()


def test_full_train_step_dp_sharded_batch_argument():
    """Regression: a dp-sharded batch passed as a jit ARGUMENT to the full
    train step at dp2 x pp2 x tp2 used to trip an XLA SPMD-partitioner
    grouping CHECK (spmd_partitioner_util.cc) because the dp sharding
    entered the pp-manual shard_map on an auto axis.  dp is manual in the
    pipeline shard_map now; this compiles + executes the whole step the
    way the training driver invokes it."""
    from megatron_llm_tpu.training.step import (TrainState,
                                                guard_spec,
                                                init_train_state,
                                                make_train_step)
    from megatron_llm_tpu.training import optimizer as opt_lib

    par = ParallelConfig(data_parallel=2, pipeline_parallel=2,
                         tensor_parallel=2, num_microbatches=4,
                         use_distributed_optimizer=True)
    cfg = tiny_config(
        hidden_size=64, num_layers=4, num_attention_heads=8,
        num_kv_heads=8, ffn_hidden_size=128, vocab_size=256,
        seq_length=32, make_vocab_size_divisible_by=16)
    rt = RuntimeConfig(model=cfg, parallel=par,
                       optimizer=OptimizerConfig(lr=1e-3, clip_grad=1.0),
                       train=TrainConfig(seq_length=32, micro_batch_size=2,
                                         global_batch_size=16,
                                         train_iters=2)).validate()
    mesh = mesh_lib.build_mesh(par)
    with mesh:
        params = model_lib.init_params(jax.random.key(0), cfg, tp=2)
        pspecs = shard_lib.param_specs(cfg, par)
        params = pipe.to_pipeline_params(params, par)
        pspecs = pipe.pipeline_param_specs(pspecs, par)
        params = shard_lib.shard_params(params, pspecs, mesh)
        state = init_train_state(rt, params)
        ospecs = opt_lib.opt_state_specs(pspecs, params, par, state.opt)
        state_spec = TrainState(params=pspecs, opt=ospecs, iteration=P(),
                                skipped=P(), guard=guard_spec())
        state_sharding = jax.tree.map(
            lambda s: NamedSharding(mesh, s), state_spec,
            is_leaf=lambda x: isinstance(x, P))
        state = jax.tree.map(lambda x, s: jax.device_put(x, s),
                             state, state_sharding)
        bsh = NamedSharding(mesh, P(None, "dp", "cp"))
        toks = np.random.default_rng(0).integers(0, 256, (4, 4, 32))
        batch = {
            "tokens": jnp.asarray(toks, jnp.int32),
            "labels": jnp.asarray(np.roll(toks, -1, -1), jnp.int32),
            "loss_mask": jnp.ones((4, 4, 32), jnp.float32),
        }
        batch = jax.tree.map(lambda x: jax.device_put(x, bsh), batch)
        step = make_train_step(rt, mesh, state_sharding,
                               jax.tree.map(lambda _: bsh, batch))
        state, metrics = step(state, batch, jax.random.key(7))
        assert np.isfinite(float(metrics["loss"]))
        assert int(state.iteration) == 1


def test_auto_remat_window_matches_unwindowed():
    """pipeline_remat_window=-1 picks W from the memory model; loss AND
    grads (the windowed path only changes the backward replay) must be
    identical to the plain schedule, including ragged padding ticks."""
    pp, M = 2, 20
    # recompute="full" (c=1) keeps the auto denominator small so the
    # chosen W lands strictly between 1 and T
    cfg = tiny_config(num_layers=4, params_dtype="float32",
                      recompute="full", seq_length=32,
                      max_position_embeddings=32)
    base = ParallelConfig(pipeline_parallel=pp, num_microbatches=M)
    auto = ParallelConfig(pipeline_parallel=pp, num_microbatches=M,
                          pipeline_remat_window=-1).validate()
    w = pipe.auto_remat_window(cfg, pp=pp, vpp=1, M=M)
    T = M + pp - 1
    assert 1 < w < T  # a real window, with -(-T // w) * w > T padding
    # the analytic estimator resolves the sentinel the same way
    est = pipe.pipeline_activation_bytes(
        cfg, pp=pp, vpp=1, M=M, mb=2, seq_shard=cfg.seq_length,
        recompute=cfg.recompute, window=-1)
    est_w = pipe.pipeline_activation_bytes(
        cfg, pp=pp, vpp=1, M=M, mb=2, seq_shard=cfg.seq_length,
        recompute=cfg.recompute, window=w)
    assert est == est_w
    mesh = mesh_lib.build_mesh(base)

    params = model_lib.init_params(jax.random.key(5), cfg)
    batch = _batch(cfg, M, mb=2, seed=13)
    p_params = pipe.to_pipeline_params(params, base)
    specs = shard_lib.param_specs(cfg, base)
    p_specs = pipe.pipeline_param_specs(specs, base)
    p_params = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        p_params, p_specs, is_leaf=lambda v: isinstance(v, P))

    def runtime(par):
        return RuntimeConfig(model=cfg, parallel=par,
                             optimizer=OptimizerConfig(),
                             train=TrainConfig(seq_length=cfg.seq_length))

    with mesh_lib.use_mesh(mesh):
        loss_plain, grads_plain = jax.jit(jax.value_and_grad(
            lambda p: pipe.pipeline_loss(runtime(base), p, batch, mesh=mesh)
        ))(p_params)
        loss_auto, grads_auto = jax.jit(jax.value_and_grad(
            lambda p: pipe.pipeline_loss(runtime(auto), p, batch, mesh=mesh)
        ))(p_params)
    np.testing.assert_allclose(np.asarray(loss_auto),
                               np.asarray(loss_plain), rtol=1e-6, atol=1e-6)
    for (path, a), (_, b) in zip(
        jax.tree.leaves_with_path(grads_plain),
        jax.tree.leaves_with_path(grads_auto),
    ):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=1e-5, atol=1e-6,
            err_msg=f"auto-window grad mismatch at "
                    f"{jax.tree_util.keystr(path)}")


def test_tight_schedule_dataflow_simulation():
    """Exhaustive pure-Python check of the tight group-interleaved index
    algebra (no XLA): simulate the ring for many (pp, vpp, M) and assert
    (a) every stage-0 re-entry tick receives exactly the (m, chunk-1)
    output the last stage emitted the tick before, (b) every microbatch
    finishes every chunk exactly once, (c) the head fires exactly M times
    on the last stage with the right microbatch ids.  The compiled
    exactness tests cover a handful of shapes; this covers the lattice.
    """
    def run(pp, vpp, M):
        T = M * vpp + pp - 1

        def work(stage, t):
            rel = t - stage
            if rel < 0 or rel >= M * vpp:
                return None
            # the SAME helper the compiled tick body and head use
            return pipe.tight_indices(rel, pp, vpp)

        finished = []
        for t in range(T):
            for s in range(pp):
                w = work(s, t)
                if w is None:
                    continue
                m, c = w
                assert 0 <= m < M, (pp, vpp, M, t, s, w)
                if s == 0 and c > 0:
                    # tight re-entry: last stage must have produced
                    # (m, c-1) at tick t-1
                    prev = work(pp - 1, t - 1)
                    assert prev == (m, c - 1), (pp, vpp, M, t, prev, (m, c))
                if s > 0:
                    # ring: previous stage produced (m, c) last tick
                    prev = work(s - 1, t - 1)
                    assert prev == (m, c), (pp, vpp, M, t, s, prev, (m, c))
                if s == pp - 1 and c == vpp - 1:
                    finished.append(m)
        assert sorted(finished) == list(range(M)), (pp, vpp, M, finished)

    for pp in (2, 3, 4, 8):
        for vpp in (1, 2, 3, 4):
            for mult in (1, 2, 3, 8):
                run(pp, vpp, pp * mult)
