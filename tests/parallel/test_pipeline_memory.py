"""Pipeline activation memory: measured (XLA) vs the analytic model.

VERDICT round 1 flagged pipeline memory scaling as the #1 design risk: the
old implementation held three fp32 [M, mb, s, h] buffers on every device.
The streamed pipeline (parallel/pipeline.py) carries only int32 tokens and
scalar losses across the shard_map boundary; this test compiles the real
train-step gradient at a BASELINE-config-5 *shape* (pp=8, M=16, scaled-down
dims) and asserts XLA's measured temp memory stays within the analytic
model of docs/pipeline_memory.md.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from megatron_llm_tpu.config import (
    OptimizerConfig,
    ParallelConfig,
    RuntimeConfig,
    TrainConfig,
    tiny_config,
)
from megatron_llm_tpu.models import model as model_lib
from megatron_llm_tpu.models import sharding as shard_lib
from megatron_llm_tpu.parallel import mesh as mesh_lib
from megatron_llm_tpu.parallel import pipeline as pipe


def _measure_temp_bytes(cfg, runtime, parallel, mesh, M, mb):
    """Peak XLA temp bytes of grad(pipeline_loss) per device."""
    params = model_lib.init_params(jax.random.key(0), cfg)
    p_params = pipe.to_pipeline_params(params, parallel)
    specs = shard_lib.param_specs(cfg, parallel)
    p_specs = pipe.pipeline_param_specs(specs, parallel)
    p_params = jax.tree.map(
        lambda x, sp: jax.device_put(x, NamedSharding(mesh, sp)),
        p_params, p_specs, is_leaf=lambda v: isinstance(v, P))

    s = cfg.seq_length
    batch = {
        "tokens": jnp.zeros((M, mb, s), jnp.int32),
        "labels": jnp.zeros((M, mb, s), jnp.int32),
        "loss_mask": jnp.ones((M, mb, s), jnp.float32),
    }

    def loss_fn(p):
        return pipe.pipeline_loss(runtime, p, batch, mesh=mesh)

    with mesh_lib.use_mesh(mesh):
        compiled = jax.jit(jax.grad(loss_fn)).lower(p_params).compile()
    stats = compiled.memory_analysis()
    assert stats is not None
    # temp_size is the whole-program pool across the 8 virtual CPU devices
    # sharing one process; normalize per device for the per-chip model.
    return stats.temp_size_in_bytes / len(jax.devices())


@pytest.mark.parametrize("vpp,M", [(1, 16), (2, 16)])
def test_streamed_pipeline_memory_fits_model(vpp, M):
    """70B/pp=8-shaped run (scaled dims): measured ≤ analytic upper bound."""
    pp, mb = 8, 1
    cfg = tiny_config(
        num_layers=pp * vpp * 2,
        hidden_size=128,
        num_attention_heads=4,
        ffn_hidden_size=256,
        params_dtype="float32",
        recompute="full",
        seq_length=512,
        max_position_embeddings=512,
        vocab_size=1024,
    )
    parallel = ParallelConfig(pipeline_parallel=pp,
                              virtual_pipeline_stages=vpp,
                              num_microbatches=M)
    runtime = RuntimeConfig(model=cfg, parallel=parallel,
                            optimizer=OptimizerConfig(),
                            train=TrainConfig(seq_length=cfg.seq_length))
    mesh = mesh_lib.build_mesh(parallel)

    measured = _measure_temp_bytes(cfg, runtime, parallel, mesh, M, mb)
    model = pipe.pipeline_activation_bytes(
        cfg, pp=pp, vpp=vpp, M=M, mb=mb, seq_shard=cfg.seq_length,
        recompute="full")
    # fp32 grad accumulators for the stage-local layer params ride in the
    # temp pool too; add them to the bound (they are param-, not
    # activation-, proportional).
    params = model_lib.init_params(jax.random.key(0), cfg)
    param_bytes = 2 * 4 * sum(
        p.size for p in jax.tree.leaves(params)) / pp
    bound = model["upper_bound"] + param_bytes * 4

    assert measured <= bound, (
        f"measured temp {measured/2**20:.1f} MiB exceeds analytic bound "
        f"{bound/2**20:.1f} MiB (terms: { {k: round(v/2**20, 2) for k, v in model.items()} })"
    )
    # And the bound itself must rule out the round-1 design: x_all +
    # outputs alone were 2 fp32 [M, mb, s, h] buffers per device.
    old_design_floor = 2 * M * mb * cfg.seq_length * cfg.hidden_size * 4
    assert model["boundary"] + model["circ"] < 3 * old_design_floor


def test_memory_scales_with_T_not_quadratically():
    """Doubling M must grow temp ≈ linearly (streamed residuals), giving
    the model predictive power for BASELINE extrapolation."""
    pp, mb, vpp = 4, 1, 1
    cfg = tiny_config(
        num_layers=8, hidden_size=128, num_attention_heads=4,
        ffn_hidden_size=256, params_dtype="float32", recompute="full",
        seq_length=256, max_position_embeddings=256, vocab_size=512,
    )

    def measure(M):
        parallel = ParallelConfig(pipeline_parallel=pp,
                                  num_microbatches=M)
        runtime = RuntimeConfig(model=cfg, parallel=parallel,
                                optimizer=OptimizerConfig(),
                                train=TrainConfig(seq_length=cfg.seq_length))
        mesh = mesh_lib.build_mesh(parallel)
        return _measure_temp_bytes(cfg, runtime, parallel, mesh, M, mb)

    m8, m16 = measure(8), measure(16)
    # T(16)/T(8) = 19/11 ≈ 1.73; allow fixed costs + XLA slop but rule out
    # anything superlinear in M (old design: 3 buffers × M + residuals × T)
    assert m16 / m8 < 2.5, (m8, m16)


def test_windowed_remat_bounds_memory_at_large_M():
    """BASELINE config-5 grad-accum regime (M=64): the windowed schedule
    must cut measured temp memory vs the plain scan and stay within its
    own analytic bound — the ≤pp-in-flight property the reference gets
    from 1F1B interleaving (megatron/schedules.py:606-722)."""
    pp, mb, M, W = 8, 1, 64, 8
    cfg = tiny_config(
        num_layers=pp * 2,
        hidden_size=128,
        num_attention_heads=4,
        ffn_hidden_size=256,
        params_dtype="float32",
        recompute="full",
        seq_length=512,
        max_position_embeddings=512,
        vocab_size=1024,
    )

    def measure(window):
        parallel = ParallelConfig(pipeline_parallel=pp, num_microbatches=M,
                                  pipeline_remat_window=window).validate()
        runtime = RuntimeConfig(model=cfg, parallel=parallel,
                                optimizer=OptimizerConfig(),
                                train=TrainConfig(seq_length=cfg.seq_length))
        mesh = mesh_lib.build_mesh(parallel)
        return _measure_temp_bytes(cfg, runtime, parallel, mesh, M, mb)

    plain = measure(0)
    windowed = measure(W)
    assert windowed < 0.6 * plain, (plain, windowed)

    model = pipe.pipeline_activation_bytes(
        cfg, pp=pp, vpp=1, M=M, mb=mb, seq_shard=cfg.seq_length,
        recompute="full", window=W)
    params = model_lib.init_params(jax.random.key(0), cfg)
    param_bytes = 2 * 4 * sum(p.size for p in jax.tree.leaves(params)) / pp
    bound = model["upper_bound"] + param_bytes * 4
    assert windowed <= bound, (
        f"windowed temp {windowed/2**20:.1f} MiB exceeds bound "
        f"{bound/2**20:.1f} MiB "
        f"({ {k: round(v/2**20, 2) for k, v in model.items()} })")


def test_windowed_remat_bounds_memory_vpp2_large_M():
    """Config-5 grad-accum regime WITH interleaving (vpp=2, M=64): the
    tight schedule has no circular buffer, so windowing must bound memory
    exactly as at vpp=1 (VERDICT r3 weak #3)."""
    pp, mb, M, W, vpp = 8, 1, 64, 8, 2
    cfg = tiny_config(
        num_layers=pp * vpp,
        hidden_size=128,
        num_attention_heads=4,
        ffn_hidden_size=256,
        params_dtype="float32",
        recompute="full",
        seq_length=512,
        max_position_embeddings=512,
        vocab_size=1024,
    )

    def measure(window):
        parallel = ParallelConfig(pipeline_parallel=pp, num_microbatches=M,
                                  virtual_pipeline_stages=vpp,
                                  pipeline_remat_window=window).validate()
        runtime = RuntimeConfig(model=cfg, parallel=parallel,
                                optimizer=OptimizerConfig(),
                                train=TrainConfig(seq_length=cfg.seq_length))
        mesh = mesh_lib.build_mesh(parallel)
        return _measure_temp_bytes(cfg, runtime, parallel, mesh, M, mb)

    plain = measure(0)
    windowed = measure(W)
    # T = M*vpp + pp - 1 = 135 saved boundaries plain vs ~O(T/W + 2W)
    assert windowed < 0.6 * plain, (plain, windowed)

    model = pipe.pipeline_activation_bytes(
        cfg, pp=pp, vpp=vpp, M=M, mb=mb, seq_shard=cfg.seq_length,
        recompute="full", window=W)
    assert model["circ"] == 0  # tight schedule: no re-entry buffer
    params = model_lib.init_params(jax.random.key(0), cfg)
    param_bytes = 2 * 4 * sum(p.size for p in jax.tree.leaves(params)) / pp
    bound = model["upper_bound"] + param_bytes * 4
    assert windowed <= bound, (
        f"windowed temp {windowed/2**20:.1f} MiB exceeds bound "
        f"{bound/2**20:.1f} MiB "
        f"({ {k: round(v/2**20, 2) for k, v in model.items()} })")
