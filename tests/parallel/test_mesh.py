"""Unit tests for parallel/mesh.py topology helpers.

``pipeline_stage_layers`` / ``stage_layer_ranges`` are the single
source of truth for which layers live on which pipeline stage — both
the training 1F1B schedule and the serving layer-sharded layout
(models/sharding.py:serving_param_specs, engine.kv_snapshot's stage
section) derive from them, so their edge cases get pinned here.
"""

import numpy as np
import pytest

from megatron_llm_tpu.config import ParallelConfig
from megatron_llm_tpu.parallel import mesh as mesh_lib


def test_stage_layers_even_split():
    assert mesh_lib.pipeline_stage_layers(8, 2) == [4, 4]
    assert mesh_lib.pipeline_stage_layers(8, 4) == [2, 2, 2, 2]


def test_stage_layers_pp1_degenerate():
    # pp=1 is the single-stage identity: one chunk holding everything
    assert mesh_lib.pipeline_stage_layers(5, 1) == [5]
    assert mesh_lib.stage_layer_ranges(5, 1) == [(0, 5)]


def test_stage_layers_vpp_chunks():
    # vpp>1 splits each stage into virtual chunks: pp·vpp entries
    assert mesh_lib.pipeline_stage_layers(8, 2, vpp=2) == [2, 2, 2, 2]
    assert mesh_lib.pipeline_stage_layers(12, 2, vpp=3) == [2] * 6


def test_stage_layers_indivisible_asserts():
    with pytest.raises(AssertionError, match="must divide"):
        mesh_lib.pipeline_stage_layers(7, 2)
    with pytest.raises(AssertionError, match="must divide"):
        mesh_lib.pipeline_stage_layers(8, 2, vpp=3)


def test_stage_layer_ranges_cover_contiguously():
    ranges = mesh_lib.stage_layer_ranges(8, 4)
    assert ranges == [(0, 2), (2, 4), (4, 6), (6, 8)]
    # ranges tile [0, L) exactly: no gaps, no overlap
    flat = [i for lo, hi in ranges for i in range(lo, hi)]
    assert flat == list(range(8))


def test_build_mesh_axis_order_and_fsdp(devices):
    mesh = mesh_lib.build_mesh(
        ParallelConfig(pipeline_parallel=2, fsdp=2, data_parallel=2))
    assert mesh.axis_names == mesh_lib.AXIS_ORDER
    assert mesh_lib.pipeline_parallel_size(mesh) == 2
    assert mesh_lib.fsdp_size(mesh) == 2
    assert mesh_lib.data_parallel_size(mesh) == 2
    # the always-size-1 named sequence axis resolves on every mesh
    assert mesh.shape[mesh_lib.SEQ_AXIS] == 1
    # single-device meshes carry the same 7-axis order
    single = mesh_lib.single_device_mesh()
    assert single.axis_names == mesh_lib.AXIS_ORDER
    assert mesh_lib.fsdp_size(single) == 1


def test_replica_submeshes_include_fsdp(devices):
    meshes = mesh_lib.replica_submeshes(
        ParallelConfig(pipeline_parallel=2, fsdp=2), 2)
    assert len(meshes) == 2
    ids = [sorted(d.id for d in np.asarray(m.devices).ravel())
           for m in meshes]
    assert len(ids[0]) == 4  # pp·fsdp devices per replica
    assert not set(ids[0]) & set(ids[1])
