"""Pipelined eval must produce the same registry metrics as pp=1.

The reference computes validation metrics at any parallelism
(megatron/metrics.py:62-110 runs wherever the last stage's logits land);
here the streamed pipeline emits per-token stats from inside the tick loop
and the metric values must match the plain forward-only eval step exactly.
"""

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from megatron_llm_tpu.config import (
    OptimizerConfig,
    ParallelConfig,
    RuntimeConfig,
    TrainConfig,
    tiny_config,
)
from megatron_llm_tpu.models import model as model_lib
from megatron_llm_tpu.models import sharding as shard_lib
from megatron_llm_tpu.parallel import mesh as mesh_lib
from megatron_llm_tpu.parallel import pipeline as pipe
from megatron_llm_tpu.training import driver as driver_lib

METRICS = ("perplexity", "accuracy", "instruct_accuracy",
           "count_loss_mask", "count_instruct_mask")


@pytest.mark.parametrize("pp,vpp", [(2, 1), (2, 2), (4, 1)])
def test_pipeline_eval_metrics_match_unpipelined(pp, vpp):
    M, mb = 4, 2
    cfg = tiny_config(
        num_layers=pp * vpp * 2,
        params_dtype="float32",
        recompute="none",
        seq_length=32,
        max_position_embeddings=32,
    )
    parallel = ParallelConfig(pipeline_parallel=pp,
                              virtual_pipeline_stages=vpp,
                              num_microbatches=M)
    runtime = RuntimeConfig(model=cfg, parallel=parallel,
                            optimizer=OptimizerConfig(),
                            train=TrainConfig(seq_length=cfg.seq_length,
                                              metrics=METRICS))
    mesh = mesh_lib.build_mesh(parallel)

    params = model_lib.init_params(jax.random.key(0), cfg)
    g = np.random.default_rng(7)
    s = cfg.seq_length
    batch = {
        "tokens": np.asarray(
            g.integers(0, cfg.vocab_size, (M, mb, s)), np.int32),
        "labels": np.asarray(
            g.integers(0, cfg.vocab_size, (M, mb, s)), np.int32),
        # mixed weights: exercises instruct_accuracy's >=1.0 threshold
        "loss_mask": np.asarray(
            g.choice([0.0, 0.3, 1.0], (M, mb, s)), np.float32),
    }

    # --- unpipelined reference metrics ---
    ref_runtime = RuntimeConfig(model=cfg, parallel=ParallelConfig(),
                                optimizer=OptimizerConfig(),
                                train=TrainConfig(seq_length=cfg.seq_length,
                                                  metrics=METRICS))
    flat = {k: v.reshape((-1,) + v.shape[2:]) for k, v in batch.items()}
    ref_step = driver_lib.make_eval_step(ref_runtime, METRICS)
    ref_out = jax.device_get(ref_step(params, flat))

    # --- pipelined metrics ---
    p_params = pipe.to_pipeline_params(params, parallel)
    specs = shard_lib.param_specs(cfg, parallel)
    p_specs = pipe.pipeline_param_specs(specs, parallel)
    p_params = jax.tree.map(
        lambda x, sp: jax.device_put(x, NamedSharding(mesh, sp)),
        p_params, p_specs, is_leaf=lambda v: isinstance(v, P))

    with mesh_lib.use_mesh(mesh):
        pp_step = driver_lib.make_pipeline_eval_step(runtime, mesh, METRICS)
        pp_out = jax.device_get(pp_step(p_params, batch))

    assert set(pp_out) == set(ref_out)
    for k in ref_out:
        # rtol covers f32 fusion differences between the flat [M*mb, s]
        # reference forward and the per-microbatch pipelined forward
        np.testing.assert_allclose(
            pp_out[k], ref_out[k], rtol=1e-3, atol=1e-5,
            err_msg=f"metric {k} diverges between pp={pp} and pp=1")
