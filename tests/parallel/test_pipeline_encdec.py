"""Split-rank encoder/decoder pipeline correctness vs the unpipelined
models (reference: pipeline_model_parallel_split_rank,
megatron/core/parallel_state.py:110-112 — validated there only by real
multi-GPU runs; here exactly on the hermetic 8-device CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from megatron_llm_tpu.config import (
    ModelConfig,
    OptimizerConfig,
    ParallelConfig,
    RuntimeConfig,
    TrainConfig,
)
from megatron_llm_tpu.models import encdec
from megatron_llm_tpu.parallel import mesh as mesh_lib
from megatron_llm_tpu.parallel import pipeline_encdec as pipe


def _t5_cfg(num_layers=4, num_decoder_layers=4, **over):
    base = dict(
        vocab_size=96, hidden_size=48, num_layers=num_layers,
        num_decoder_layers=num_decoder_layers, num_attention_heads=4,
        num_kv_heads=4, ffn_hidden_size=96, max_position_embeddings=64,
        norm_type="layernorm", activation="gelu",
        position_embedding_type="absolute", use_bias=True,
        tie_embed_logits=True, tokentype_size=0,
        params_dtype="float32", attention_impl="dot", recompute="none",
        make_vocab_size_divisible_by=8, seq_length=32,
    )
    base.update(over)
    return ModelConfig(**base).validate()


def _bert_cfg(num_layers=4, **over):
    return _t5_cfg(num_layers=num_layers, num_decoder_layers=None,
                   tokentype_size=2, **over)


def _runtime(cfg, parallel):
    return RuntimeConfig(model=cfg, parallel=parallel,
                         optimizer=OptimizerConfig(),
                         train=TrainConfig(seq_length=cfg.seq_length))


def _t5_batch(cfg, M, mb, s_enc, s_dec, seed=0):
    g = np.random.default_rng(seed)
    v = cfg.vocab_size
    enc_pad = np.ones((M, mb, s_enc), np.float32)
    dec_pad = np.ones((M, mb, s_dec), np.float32)
    # ragged padding in both streams exercises the bias masking
    enc_pad[:, :, s_enc - 3:] = 0.0
    dec_pad[:, 0, s_dec - 2:] = 0.0
    return {
        "enc_tokens": jnp.asarray(
            g.integers(0, v, (M, mb, s_enc)), jnp.int32),
        "dec_tokens": jnp.asarray(
            g.integers(0, v, (M, mb, s_dec)), jnp.int32),
        "labels": jnp.asarray(g.integers(0, v, (M, mb, s_dec)), jnp.int32),
        "loss_mask": jnp.asarray(dec_pad),
        "enc_pad_mask": jnp.asarray(enc_pad),
        "dec_pad_mask": jnp.asarray(dec_pad),
    }


def _bert_batch(cfg, M, mb, s, seed=0):
    g = np.random.default_rng(seed)
    v = cfg.vocab_size
    pad = np.ones((M, mb, s), np.float32)
    pad[:, :, s - 3:] = 0.0
    return {
        "tokens": jnp.asarray(g.integers(0, v, (M, mb, s)), jnp.int32),
        "pad_mask": jnp.asarray(pad),
        "labels": jnp.asarray(g.integers(0, v, (M, mb, s)), jnp.int32),
        "loss_mask": jnp.asarray(pad * (g.random((M, mb, s)) < 0.3)),
        "tokentype_ids": jnp.asarray(
            g.integers(0, 2, (M, mb, s)), jnp.int32),
        "is_random": jnp.asarray(g.integers(0, 2, (M, mb)), jnp.int32),
    }


def _t5_reference_loss(cfg, params, batch):
    M = batch["enc_tokens"].shape[0]

    def one(m):
        return encdec.t5_loss(cfg, params, {
            k: batch[k][m] for k in batch})

    return jnp.mean(jax.vmap(one)(jnp.arange(M)))


def _bert_reference_loss(cfg, params, batch):
    M = batch["tokens"].shape[0]

    def one(m):
        return encdec.bert_loss(cfg, params,
                                {k: batch[k][m] for k in batch})

    return jnp.mean(jax.vmap(one)(jnp.arange(M)))


def _place(staged, specs, mesh):
    return jax.tree.map(
        lambda x, sp: jax.device_put(x, NamedSharding(mesh, sp)),
        staged, specs, is_leaf=lambda v: isinstance(v, P))


@pytest.mark.parametrize(
    "dp,pp,tp,split,M,s_enc,s_dec,W",
    [
        (1, 2, 1, 1, 3, 32, 32, 0),     # minimal split: 1 enc + 1 dec stage
        (1, 4, 1, 2, 4, 32, 16, 0),     # uneven seq lengths (padded carry)
        (2, 2, 2, 1, 4, 32, 32, 0),     # dp x pp x tp composed
        (1, 4, 1, 2, 6, 32, 32, 3),     # windowed remat over the tick loop
        (1, 4, 1, 1, 4, 16, 32, 0),     # asymmetric split (1 enc, 3 dec)
    ],
)
def test_t5_pipeline_matches_reference(dp, pp, tp, split, M, s_enc, s_dec,
                                       W):
    enc_stages, dec_stages = split, pp - split
    lpc = 2
    cfg = _t5_cfg(num_layers=enc_stages * lpc,
                  num_decoder_layers=dec_stages * lpc,
                  seq_length=max(s_enc, s_dec),
                  max_position_embeddings=max(s_enc, s_dec))
    parallel = ParallelConfig(
        data_parallel=dp, pipeline_parallel=pp, tensor_parallel=tp,
        pipeline_split_rank=split, num_microbatches=M,
        pipeline_remat_window=W,
    ).validate()
    mesh = mesh_lib.build_mesh(parallel)

    params = encdec.init_t5_params(jax.random.key(0), cfg)
    batch = _t5_batch(cfg, M, mb=2, s_enc=s_enc, s_dec=s_dec)

    ref_loss = _t5_reference_loss(cfg, params, batch)
    ref_grads = jax.grad(
        lambda p: _t5_reference_loss(cfg, p, batch))(params)

    staged = pipe.t5_to_pipeline_params(params, parallel)
    specs = pipe.t5_pipeline_param_specs(cfg, parallel)
    staged = _place(staged, specs, mesh)
    runtime = _runtime(cfg, parallel)

    with mesh_lib.use_mesh(mesh):
        pl_loss = jax.jit(
            lambda p, b: pipe.t5_pipeline_loss(runtime, p, b, mesh=mesh)
        )(staged, batch)
        pl_grads = jax.jit(jax.grad(
            lambda p: pipe.t5_pipeline_loss(runtime, p, batch, mesh=mesh)
        ))(staged)

    np.testing.assert_allclose(np.asarray(pl_loss), np.asarray(ref_loss),
                               rtol=2e-5, atol=2e-5)

    # grads: map the staged layout back and compare every leaf
    back = pipe.t5_from_pipeline_params(pl_grads, parallel)
    flat_ref, _ = jax.tree_util.tree_flatten_with_path(ref_grads)
    flat_got = dict(jax.tree_util.tree_flatten_with_path(back)[0])
    for path, g_ref in flat_ref:
        g_got = flat_got[path]
        np.testing.assert_allclose(
            np.asarray(g_got), np.asarray(g_ref), rtol=1e-4, atol=1e-4,
            err_msg=jax.tree_util.keystr(path))


def test_t5_pipeline_dummy_cross_grads_are_zero():
    """Encoder stages' zero cross-attention weights must receive exactly
    zero cotangents (the is_decoder mask), so they stay a fixed point of
    training and never perturb encoder math."""
    pp, split, lpc, M = 2, 1, 2, 3
    cfg = _t5_cfg(num_layers=split * lpc,
                  num_decoder_layers=(pp - split) * lpc)
    parallel = ParallelConfig(
        pipeline_parallel=pp, pipeline_split_rank=split,
        num_microbatches=M).validate()
    mesh = mesh_lib.build_mesh(parallel)
    params = encdec.init_t5_params(jax.random.key(0), cfg)
    batch = _t5_batch(cfg, M, mb=2, s_enc=32, s_dec=32)
    staged = pipe.t5_to_pipeline_params(params, parallel)
    staged = _place(staged, pipe.t5_pipeline_param_specs(cfg, parallel),
                    mesh)
    runtime = _runtime(cfg, parallel)
    with mesh_lib.use_mesh(mesh):
        grads = jax.jit(jax.grad(
            lambda p: pipe.t5_pipeline_loss(runtime, p, batch, mesh=mesh)
        ))(staged)
    for leaf in jax.tree.leaves(
            jax.tree.map(lambda g: g[:split], grads["cross"])):
        assert float(jnp.abs(leaf).max()) == 0.0
    # ...while the real (decoder-stage) cross weights train
    total = sum(float(jnp.abs(leaf[split:]).sum())
                for leaf in jax.tree.leaves(grads["cross"]))
    assert total > 0.0


@pytest.mark.parametrize(
    "dp,pp,tp,M,W",
    [
        (1, 2, 1, 3, 0),
        (1, 4, 1, 4, 0),
        (2, 2, 2, 4, 0),
        (1, 4, 1, 6, 3),   # windowed remat
    ],
)
def test_bert_pipeline_matches_reference(dp, pp, tp, M, W):
    cfg = _bert_cfg(num_layers=pp * 2)
    parallel = ParallelConfig(
        data_parallel=dp, pipeline_parallel=pp, tensor_parallel=tp,
        num_microbatches=M, pipeline_remat_window=W,
    ).validate()
    mesh = mesh_lib.build_mesh(parallel)

    params = encdec.init_bert_params(jax.random.key(0), cfg)
    batch = _bert_batch(cfg, M, mb=2, s=32)

    ref_loss = _bert_reference_loss(cfg, params, batch)
    ref_grads = jax.grad(
        lambda p: _bert_reference_loss(cfg, p, batch))(params)

    staged = pipe.bert_to_pipeline_params(params, parallel)
    specs = pipe.bert_pipeline_param_specs(cfg, parallel)
    staged = _place(staged, specs, mesh)
    runtime = _runtime(cfg, parallel)

    with mesh_lib.use_mesh(mesh):
        pl_loss = jax.jit(
            lambda p, b: pipe.bert_pipeline_loss(runtime, p, b, mesh=mesh)
        )(staged, batch)
        pl_grads = jax.jit(jax.grad(
            lambda p: pipe.bert_pipeline_loss(runtime, p, batch, mesh=mesh)
        ))(staged)

    np.testing.assert_allclose(np.asarray(pl_loss), np.asarray(ref_loss),
                               rtol=2e-5, atol=2e-5)

    back = pipe.bert_from_pipeline_params(pl_grads, parallel)
    flat_ref, _ = jax.tree_util.tree_flatten_with_path(ref_grads)
    flat_got = dict(jax.tree_util.tree_flatten_with_path(back)[0])
    for path, g_ref in flat_ref:
        np.testing.assert_allclose(
            np.asarray(flat_got[path]), np.asarray(g_ref),
            rtol=1e-4, atol=1e-4, err_msg=jax.tree_util.keystr(path))


def test_split_rank_validation():
    with pytest.raises(AssertionError):
        ParallelConfig(pipeline_parallel=4,
                       pipeline_split_rank=4).validate()
    with pytest.raises(AssertionError):
        ParallelConfig(pipeline_parallel=4,
                       pipeline_split_rank=0).validate()
    # unequal layers-per-chunk across the split is rejected with a message
    cfg = _t5_cfg(num_layers=4, num_decoder_layers=2)
    parallel = ParallelConfig(pipeline_parallel=2, pipeline_split_rank=1,
                              num_microbatches=2).validate()
    params = encdec.init_t5_params(jax.random.key(0), cfg)
    with pytest.raises(AssertionError, match="layers-per-stage"):
        pipe.t5_to_pipeline_params(params, parallel)
