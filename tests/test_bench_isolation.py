"""bench.py partial-failure behavior: any single point crashing (even with
a deterministic error) must still yield one parsed JSON record.

Round 2's bench measured the whole train curve, then lost it when the
decode point crashed before the single end-of-run print; deterministic
errors were also retried as if transient.  These tests pin the fixed
orchestration, with the heavy measurement functions stubbed out.
"""

import json
import io
import contextlib

import pytest

import bench


def _run_main(monkeypatch, train_fn, decode_fn):
    monkeypatch.setattr(bench, "_train_point", train_fn)
    monkeypatch.setattr(bench, "_decode_point", decode_fn)
    # the real probe subprocesses to the accelerator (and waits out its
    # timeout when the tunnel is down) — not what these tests measure
    monkeypatch.setattr(bench, "_detect_device", lambda: "TPU v5 lite")
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.main()
    lines = [l for l in buf.getvalue().splitlines() if not l.startswith("#")]
    assert len(lines) == 1, lines
    return json.loads(lines[0])


def _ok_train(seq, mb, rc, iters, peak, model=None):
    return 1000.0 * 1024 / seq, 0.5, 2.0, 123456


def _ok_decode(hbm_bw, quantize=False):
    # (tokens/sec, roofline tokens/sec, prefill tokens/sec)
    return ((3000.0, 8000.0, 9000.0) if quantize
            else (2000.0, 7000.0, 9000.0))


def test_all_points_ok(monkeypatch):
    rec = _run_main(monkeypatch, _ok_train, _ok_decode)
    assert rec["metric"] == "mfu" and rec["value"] == 0.5
    assert rec["decode_tokens_per_sec"] == 2000.0
    assert rec["decode_roofline_frac"] == round(2000.0 / 7000.0, 4)
    assert rec["decode_tokens_per_sec_int8"] == 3000.0
    assert rec["prefill_tokens_per_sec"] == 9000.0
    # 5 seq points + the 7B-width point
    assert len(rec["mfu_vs_seq"]) == 6
    assert any(p.get("config", "").startswith("7b-width")
               for p in rec["mfu_vs_seq"])


def test_decode_crash_keeps_headline(monkeypatch):
    def bad_decode(hbm_bw, quantize=False):
        raise NameError("boom")  # the round-2 failure class

    rec = _run_main(monkeypatch, _ok_train, bad_decode)
    assert rec["value"] == 0.5 and rec["vs_baseline"] is not None
    assert rec["decode_tokens_per_sec"] is None
    assert rec["decode_tokens_per_sec_int8"] is None
    assert len(rec["mfu_vs_seq"]) == 6


def test_one_curve_point_crash_keeps_rest(monkeypatch):
    def train(seq, mb, rc, iters, peak, model=None):
        if seq == 16384:
            raise TypeError("deterministic bug at one seq")
        return _ok_train(seq, mb, rc, iters, peak, model)

    rec = _run_main(monkeypatch, train, _ok_decode)
    assert rec["value"] == 0.5
    seqs = [p["seq_length"] for p in rec["mfu_vs_seq"]]
    assert 16384 not in seqs and 32768 in seqs


def test_headline_crash_uses_fallback_then_partial(monkeypatch):
    calls = []

    def train(seq, mb, rc, iters, peak, model=None):
        calls.append((seq, mb))
        raise ValueError("always fails")

    rec = _run_main(monkeypatch, train, _ok_decode)
    # primary + fallback headline attempted, then every curve point
    assert (1024, 12) in calls and (1024, 8) in calls
    assert rec["value"] is None and rec["mfu_vs_seq"] == []
    assert rec["decode_tokens_per_sec"] == 2000.0


def test_deterministic_error_not_retried(monkeypatch):
    calls = []

    def bad():
        calls.append(1)
        raise NameError("not transient")

    with pytest.raises(NameError):
        bench._retry(bad)
    assert len(calls) == 1


def test_transient_error_retried(monkeypatch):
    import time

    import jax

    monkeypatch.setattr(time, "sleep", lambda s: None)  # retry backoff
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) == 1:
            raise jax.errors.JaxRuntimeError("transient compile blip")
        return "ok"

    assert bench._retry(flaky) == "ok"
    assert len(calls) == 2


def test_unreachable_device_yields_structured_record(monkeypatch, capsys):
    """A wedged accelerator tunnel must produce a parseable failure
    record quickly, not an indefinite hang (observed live in round 3)."""
    def hang_forever():
        raise TimeoutError("jax.devices() exceeded 300s")

    monkeypatch.setattr(bench, "_detect_device", hang_forever)
    with pytest.raises(SystemExit):
        bench.main()
    out = [l for l in capsys.readouterr().out.splitlines()
           if not l.startswith("#")]
    rec = json.loads(out[-1])
    assert rec["value"] is None and "TimeoutError" in rec["error"]
