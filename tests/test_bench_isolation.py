"""bench.py partial-failure behavior: any single point failing must still
yield one parsed JSON record.

Round 2's bench measured the whole train curve, then lost it when the
decode point crashed before the single end-of-run print; round 5's first
run had the 32k row's HBM footprint leak into every later in-process
point.  The orchestration now runs each point in a subprocess; these
tests pin the parent's aggregation/partial-record behavior (with
``_point`` stubbed), the child protocol, and the real subprocess error
path.
"""

import contextlib
import io
import json
import sys

import pytest

import bench


def _stub_point(train=None, decode=None, pld=None, prefill=None,
                serving=None):
    """A fake bench._point dispatching on the spec kind."""
    def point(label, spec, timeout_s=900, env=None):
        kind = spec["kind"]
        try:
            if kind == "train":
                return train(spec)
            if kind == "decode":
                return decode(spec)
            if kind == "pld":
                return pld(spec) if pld else None
            if kind == "prefill":
                return prefill(spec) if prefill else None
            if kind == "serving":
                return serving(spec) if serving else None
        except Exception as e:  # noqa: BLE001 — mirrors subprocess crash
            print(f"# bench point {label} FAILED: {type(e).__name__}: {e}")
            return None
        return None
    return point


def _ok_train(spec):
    return [1000.0 * 1024 / spec["seq"], 0.5, 2.0, 123456]


def _ok_decode(spec):
    tps = 3000.0 if spec.get("quantize") else 2000.0
    return {"tokens_per_sec": tps, "roofline_tokens_per_sec": 7000.0,
            "roofline_frac": round(tps / 7000.0, 4),
            "prefill_tokens_per_sec": 9000.0, "model_params": 1}


def _run_main(monkeypatch, **stubs):
    monkeypatch.setattr(bench, "_point", _stub_point(**stubs))
    monkeypatch.setattr(bench, "_detect_device",
                        lambda: ("TPU v5 lite", 1))
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.main()
    lines = [l for l in buf.getvalue().splitlines() if not l.startswith("#")]
    assert len(lines) == 1, lines
    return json.loads(lines[0])


def test_all_points_ok(monkeypatch):
    rec = _run_main(
        monkeypatch, train=_ok_train, decode=_ok_decode,
        pld=lambda s: {"pld_tokens_per_verify_repetitive": 4.0},
        prefill=lambda s: {"prefill_long_tokens_per_sec": 30000.0,
                           "prefill_long_mfu": 0.3},
        serving=lambda s: {"serving_requests_per_sec": 2.5,
                           "serving_token_latency_ms_p95": 11.0,
                           "serving_max_decode_batch": 8})
    assert rec["metric"] == "mfu" and rec["value"] == 0.5
    assert rec["serving"]["serving_requests_per_sec"] == 2.5
    assert rec["serving"]["serving_max_decode_batch"] == 8
    assert rec["decode_tokens_per_sec"] == 2000.0
    assert rec["decode_roofline_frac"] == round(2000.0 / 7000.0, 4)
    assert rec["decode_tokens_per_sec_int8"] == 3000.0
    assert rec["prefill_tokens_per_sec"] == 9000.0
    assert rec["decode_7b_width"]["tokens_per_sec"] == 2000.0
    assert rec["pld_tokens_per_verify_repetitive"] == 4.0
    assert rec["prefill_long_mfu"] == 0.3
    # 5 seq points + the 7B-width point
    assert len(rec["mfu_vs_seq"]) == 6
    assert any(p.get("config", "").startswith("7b-width")
               for p in rec["mfu_vs_seq"])


def test_decode_crash_keeps_headline(monkeypatch):
    def bad_decode(spec):
        raise NameError("boom")  # the round-2 failure class

    rec = _run_main(monkeypatch, train=_ok_train, decode=bad_decode)
    assert rec["value"] == 0.5 and rec["vs_baseline"] is not None
    assert "decode_tokens_per_sec" not in rec
    assert "decode_7b_width" not in rec
    assert "serving" not in rec  # serving point absent → key omitted
    assert len(rec["mfu_vs_seq"]) == 6


def test_one_curve_point_crash_keeps_rest(monkeypatch):
    def train(spec):
        if spec["seq"] == 16384:
            raise TypeError("deterministic bug at one seq")
        return _ok_train(spec)

    rec = _run_main(monkeypatch, train=train, decode=_ok_decode)
    assert rec["value"] == 0.5
    seqs = [p["seq_length"] for p in rec["mfu_vs_seq"]]
    assert 16384 not in seqs and 32768 in seqs


def test_headline_crash_uses_fallback_then_partial(monkeypatch):
    calls = []

    def train(spec):
        calls.append((spec["seq"], spec["mb"]))
        raise ValueError("always fails")

    rec = _run_main(monkeypatch, train=train, decode=_ok_decode)
    # primary + fallback headline attempted, then every curve point
    assert (1024, 12) in calls and (1024, 8) in calls
    assert rec["value"] is None and rec["mfu_vs_seq"] == []
    assert rec["decode_tokens_per_sec"] == 2000.0


def test_child_protocol_roundtrip(monkeypatch, capsys):
    """_child_main prints the marker line _point parses."""
    monkeypatch.setattr(bench, "_train_point",
                        lambda *a, **kw: [1.0, 0.5, 2.0, 7])
    bench._child_main(json.dumps(
        {"kind": "train", "platform": "TPU v5 lite", "seq": 1024,
         "mb": 1, "rc": "full", "iters": 1}))
    out = capsys.readouterr().out
    marked = [l for l in out.splitlines()
              if l.startswith(bench._CHILD_MARK)]
    assert len(marked) == 1
    assert json.loads(marked[0][len(bench._CHILD_MARK):]) == [1.0, 0.5,
                                                              2.0, 7]


def test_point_subprocess_failure_returns_none(capsys):
    """A real subprocess with a bad spec fails cleanly → None + a line."""
    out = bench._point("bogus", {"kind": "no-such-kind",
                                 "platform": "TPU v5 lite"}, timeout_s=60)
    assert out is None
    assert "bogus" in capsys.readouterr().out


def test_deterministic_error_not_retried(monkeypatch):
    calls = []

    def bad():
        calls.append(1)
        raise NameError("not transient")

    with pytest.raises(NameError):
        bench._retry(bad)
    assert len(calls) == 1


def test_transient_error_retried(monkeypatch):
    import time

    import jax

    monkeypatch.setattr(time, "sleep", lambda s: None)  # retry backoff
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) == 1:
            raise jax.errors.JaxRuntimeError("transient")
        return "ok"

    assert bench._retry(flaky) == "ok"
    assert len(calls) == 2


def test_unreachable_device_yields_structured_record(monkeypatch, capsys):
    """A wedged accelerator tunnel must produce ONE parseable JSON error
    record and exit 1 — not a stack trace (the round-3 driver failure)."""
    def probe():
        raise TimeoutError("device probe exceeded 240s")

    monkeypatch.setattr(bench, "_detect_device", probe)
    with pytest.raises(SystemExit) as e:
        bench.main()
    assert e.value.code == 1
    lines = [l for l in capsys.readouterr().out.splitlines()
             if not l.startswith("#")]
    assert len(lines) == 1
    rec = json.loads(lines[0])
    assert rec["value"] is None and "TimeoutError" in rec["error"]
