"""BlockPool allocator discipline (serving/block_pool.py).

Deterministic units for the invariants the engine leans on — trash block
pinning, reservation soundness, ref counting, copy-on-write — plus a
seeded randomized storm: thousands of interleaved reserve / alloc /
share / release / COW operations across simulated requests must never
double-free, never leak a block, and keep the free list + ref counts +
reservation ledger mutually consistent at every step.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.config import tiny_config
from megatron_llm_tpu.serving.block_pool import BlockPool


@pytest.fixture(scope="module")
def cfg():
    return tiny_config(num_layers=2, vocab_size=64,
                       make_vocab_size_divisible_by=8)


def test_trash_block_is_permanently_pinned(cfg):
    pool = BlockPool(cfg, 4, 8)
    assert pool.TRASH == 0
    assert pool.ref(0) == 1
    assert pool.usable_blocks == 3          # n_blocks minus trash
    pool.decref(0)                          # explicit no-op
    assert pool.ref(0) == 1
    with pytest.raises(AssertionError):
        pool.incref(0)                      # trash is never shared


def test_reservation_guarantees_allocation(cfg):
    pool = BlockPool(cfg, 5, 8)             # 4 usable
    assert pool.can_reserve(4) and not pool.can_reserve(5)
    assert pool.reserve(3)
    assert not pool.reserve(2)              # only 1 unreserved left
    assert pool.reserve(1)
    bids = [pool.alloc_reserved() for _ in range(4)]
    assert sorted(bids) == [1, 2, 3, 4]
    assert pool.free_blocks == 0 and pool.reserved_blocks == 0
    pool.decref(bids[0])
    assert pool.free_blocks == 1


def test_decref_double_free_is_caught(cfg):
    pool = BlockPool(cfg, 3, 8)
    pool.reserve(1)
    bid = pool.alloc_reserved()
    pool.decref(bid)
    with pytest.raises(AssertionError):
        pool.decref(bid)


@pytest.mark.parametrize("quant", ["fp32", "int8"])
def test_cow_copies_shared_block_contents(cfg, quant):
    """ensure_writable on a shared block allocates a fresh block whose
    device contents equal the original's — for int8 pools both the q and
    scale leaves — and drops the caller's ref on the shared one."""
    if quant == "int8":
        cfg = dataclasses.replace(cfg, kv_cache_quant="int8")
    cow_calls = []
    pool = BlockPool(cfg, 4, 8, on_cow=lambda: cow_calls.append(1))
    pool.reserve(1)
    bid = pool.alloc_reserved()
    # write recognizable rows into the block on device
    pool.k_pool = jax.tree.map(
        lambda a: a.at[:, bid].set(jnp.ones_like(a[:, bid])), pool.k_pool)
    pool.incref(bid)                        # a second owner (prefix trie)
    pool.reserve(1)
    new = pool.ensure_writable(bid)
    assert new != bid
    assert pool.ref(bid) == 1 and pool.ref(new) == 1
    assert pool.cow_copies == 1 and cow_calls == [1]
    for leaf in jax.tree.leaves(pool.k_pool):
        np.testing.assert_array_equal(np.asarray(leaf[:, new]),
                                      np.asarray(leaf[:, bid]))
    # exclusively owned: no copy
    assert pool.ensure_writable(new) == new
    assert pool.cow_copies == 1


def test_ensure_writable_on_trash_allocates_fresh(cfg):
    """A lazily-growing slot whose table entry is still the trash block
    gets a fresh block without counting a COW copy."""
    pool = BlockPool(cfg, 3, 8)
    pool.reserve(1)
    bid = pool.ensure_writable(BlockPool.TRASH)
    assert bid != BlockPool.TRASH and pool.ref(bid) == 1
    assert pool.cow_copies == 0


def test_randomized_storm_never_leaks_or_double_frees(cfg):
    """Seeded allocator storm: simulated requests reserve worst-case
    blocks, lazily allocate, share blocks with a simulated trie, COW on
    shared boundaries, and release in random order.  After every
    operation the ledger must balance:

        free + sum(live refs' blocks) == usable
        reserved <= free

    and at the end — all requests retired, trie drained — every block is
    back on the free list.
    """
    rng = np.random.default_rng(42)
    pool = BlockPool(cfg, 34, 4)            # 33 usable
    live = {}                               # request id -> {"res": n, "bids": []}
    trie = []                               # (bid) refs held by the "trie"
    next_rid = 0

    def check_ledger():
        # every allocated block has ref >= 1; freed blocks have ref 0
        held = {b for st in live.values() for b in st["bids"]} | set(trie)
        assert pool.used_blocks >= len(held)  # sharing collapses ids
        assert pool.free_blocks + pool.used_blocks == pool.usable_blocks
        assert pool.reserved_blocks <= pool.free_blocks
        for b in held:
            assert pool.ref(b) >= 1

    for step in range(4000):
        op = rng.integers(0, 5)
        if op == 0:                          # admit: reserve worst case
            want = int(rng.integers(1, 5))
            if pool.can_reserve(want):
                live[next_rid] = {"res": want, "bids": []}
                assert pool.reserve(want)
                next_rid += 1
        elif op == 1 and live:               # grow: lazy alloc
            rid = int(rng.choice(list(live)))
            st = live[rid]
            if st["res"] > 0:
                st["bids"].append(pool.alloc_reserved())
                st["res"] -= 1
        elif op == 2 and live:               # share a block with the trie
            rid = int(rng.choice(list(live)))
            bids = live[rid]["bids"]
            if bids:
                b = int(rng.choice(bids))
                pool.incref(b)
                trie.append(b)
        elif op == 3 and live:               # COW a shared boundary block
            rid = int(rng.choice(list(live)))
            st = live[rid]
            shared = [b for b in st["bids"] if pool.ref(b) > 1]
            if shared and st["res"] > 0:
                b = int(rng.choice(shared))
                new = pool.ensure_writable(b)
                assert new != b
                st["bids"][st["bids"].index(b)] = new
                st["res"] -= 1
        elif op == 4:                        # retire a request or evict
            if live and rng.integers(0, 2):
                rid = int(rng.choice(list(live)))
                st = live.pop(rid)
                for b in st["bids"]:
                    pool.decref(b)
                if st["res"]:
                    pool.unreserve(st["res"])
            elif trie:
                pool.decref(trie.pop(int(rng.integers(0, len(trie)))))
        check_ledger()

    for st in live.values():                 # drain everything
        for b in st["bids"]:
            pool.decref(b)
        if st["res"]:
            pool.unreserve(st["res"])
    for b in trie:
        pool.decref(b)
    assert pool.used_blocks == 0
    assert pool.free_blocks == pool.usable_blocks
    assert pool.reserved_blocks == 0
    assert pool.ref_counts() == {}
