"""KV-slot surgery (models/model.py cache_slot_update/read) and the
paged SlotAllocator: free-list discipline, block-table inserts over a
shared BlockPool, and zero-copy prefix sharing via ref bumps."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.config import tiny_config
from megatron_llm_tpu.models import model as model_lib
from megatron_llm_tpu.serving import SlotAllocator
from megatron_llm_tpu.serving.block_pool import BlockPool


@pytest.fixture(scope="module")
def cfg():
    return tiny_config(num_layers=2, vocab_size=64,
                       make_vocab_size_divisible_by=8)


def test_cache_slot_update_roundtrip(cfg):
    """Writing a batch-1 cache into slot 2 of a 4-slot cache must replace
    exactly that row and leave the others untouched."""
    k_big, v_big = model_lib.init_kv_cache(cfg, 4, 16)
    k_small, v_small = model_lib.init_kv_cache(cfg, 1, 16)
    rng = np.random.default_rng(0)
    randomize = lambda a: jnp.asarray(  # noqa: E731
        rng.standard_normal(a.shape), a.dtype)
    k_small = jax.tree.map(randomize, k_small)
    v_small = jax.tree.map(randomize, v_small)

    k_big = model_lib.cache_slot_update(k_big, k_small, 2)
    v_big = model_lib.cache_slot_update(v_big, v_small, 2)
    for big, small in ((k_big, k_small), (v_big, v_small)):
        got = model_lib.cache_slot_read(big, 2)
        jax.tree.map(lambda g, s: np.testing.assert_array_equal(
            np.asarray(g), np.asarray(s)), got, small)
        for other in (0, 1, 3):  # zero-initialized rows stay zero
            jax.tree.map(lambda r: np.testing.assert_array_equal(
                np.asarray(r), 0), model_lib.cache_slot_read(big, other))


def test_cache_slot_update_pytree_aware():
    """Quantized caches are ``{"q", "scale"}`` pytrees per leaf
    (ops/kv_quant.py layout): the slot splice must update every leaf, with
    batch on axis 1."""
    big = {"q": jnp.zeros((2, 4, 8), jnp.int8),
           "scale": jnp.zeros((2, 4, 8), jnp.float32)}
    small = {"q": jnp.ones((2, 1, 8), jnp.int8),
             "scale": jnp.full((2, 1, 8), 0.5, jnp.float32)}
    out = model_lib.cache_slot_update(big, small, 3)
    np.testing.assert_array_equal(np.asarray(out["q"])[:, 3], 1)
    np.testing.assert_array_equal(np.asarray(out["scale"])[:, 3], 0.5)
    np.testing.assert_array_equal(np.asarray(out["q"])[:, :3], 0)
    np.testing.assert_array_equal(np.asarray(out["scale"])[:, :3], 0.0)
    got = model_lib.cache_slot_read(out, 3)
    np.testing.assert_array_equal(np.asarray(got["q"]),
                                  np.asarray(small["q"]))


def test_slot_allocator_free_list(cfg):
    alloc = SlotAllocator(cfg, 3, 8, BlockPool(cfg, 8, 4))
    assert alloc.free_slots == 3 and alloc.active_slots == 0
    taken = [alloc.alloc() for _ in range(3)]
    assert sorted(taken) == [0, 1, 2]
    assert alloc.alloc() is None  # exhausted
    assert alloc.active_slots == 3
    alloc.release(taken[1])
    assert alloc.free_slots == 1
    assert alloc.alloc() == taken[1]  # recycled
    with pytest.raises(AssertionError):
        alloc.release(7)  # out of range
    alloc.release(taken[0])
    with pytest.raises(AssertionError):
        alloc.release(taken[0])  # double release


def test_slot_allocator_insert_roundtrip(cfg):
    pool = BlockPool(cfg, 9, 4)
    alloc = SlotAllocator(cfg, 2, 8, pool)  # table_blocks = 2
    k1, v1 = model_lib.init_kv_cache(cfg, 1, alloc.width)
    k1 = jax.tree.map(lambda a: jnp.full_like(a, 2.0), k1)
    v1 = jax.tree.map(lambda a: jnp.full_like(a, 3.0), v1)
    slot = alloc.alloc()
    assert pool.reserve(2)
    alloc.set_reservation(slot, 2)
    alloc.insert(slot, k1, v1, n_tokens=8)
    assert pool.used_blocks == 2 and alloc.reserved[slot] == 0
    # the gathered view of the slot's table reproduces the dense insert
    tbl = jnp.asarray(alloc.tables[slot:slot + 1])
    jax.tree.map(lambda g, s: np.testing.assert_array_equal(
        np.asarray(g), np.asarray(s)),
        model_lib.cache_gather_blocks(alloc.k_pool, tbl), k1)
    jax.tree.map(lambda g, s: np.testing.assert_array_equal(
        np.asarray(g), np.asarray(s)),
        model_lib.cache_gather_blocks(alloc.v_pool, tbl), v1)
    # release drops the refs and returns the blocks to the free list
    alloc.release(slot)
    assert pool.used_blocks == 0 and pool.free_blocks == 8


def test_insert_shared_prefix_blocks_are_ref_bumps(cfg):
    """A prefix hit's shared block ids land in the table by incref —
    the scatter touches only the freshly computed tail blocks, and
    releasing either sharer never frees a block still referenced."""
    pool = BlockPool(cfg, 9, 4)
    alloc = SlotAllocator(cfg, 2, 16, pool)  # table_blocks = 4
    kd, vd = model_lib.init_kv_cache(cfg, 1, alloc.width)
    kd = jax.tree.map(lambda a: jnp.full_like(a, 1.0), kd)
    vd = jax.tree.map(lambda a: jnp.full_like(a, 1.0), vd)
    s0 = alloc.alloc()
    assert pool.reserve(3)
    alloc.set_reservation(s0, 3)
    alloc.insert(s0, kd, vd, n_tokens=12)  # blocks 0..2 of the table
    shared = [int(b) for b in alloc.tables[s0][:2]]
    cow_before = pool.cow_copies

    s1 = alloc.alloc()
    assert pool.reserve(1)  # only the non-shared tail block
    alloc.set_reservation(s1, 1)
    alloc.insert(s1, kd, vd, n_tokens=12, shared_bids=shared)
    assert [int(b) for b in alloc.tables[s1][:2]] == shared
    assert all(pool.ref(b) == 2 for b in shared)
    assert pool.cow_copies == cow_before  # pure ref bump, zero copies
    assert pool.used_blocks == 4  # 3 + 1 fresh tail, not 3 + 3

    alloc.release(s0)
    assert all(pool.ref(b) == 1 for b in shared)  # s1 keeps them alive
    alloc.release(s1)
    assert pool.used_blocks == 0
