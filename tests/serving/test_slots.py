"""KV-slot surgery (models/model.py cache_slot_update/read) and the
SlotAllocator free-list discipline."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.config import tiny_config
from megatron_llm_tpu.models import model as model_lib
from megatron_llm_tpu.serving import SlotAllocator


@pytest.fixture(scope="module")
def cfg():
    return tiny_config(num_layers=2, vocab_size=64,
                       make_vocab_size_divisible_by=8)


def test_cache_slot_update_roundtrip(cfg):
    """Writing a batch-1 cache into slot 2 of a 4-slot cache must replace
    exactly that row and leave the others untouched."""
    k_big, v_big = model_lib.init_kv_cache(cfg, 4, 16)
    k_small, v_small = model_lib.init_kv_cache(cfg, 1, 16)
    rng = np.random.default_rng(0)
    randomize = lambda a: jnp.asarray(  # noqa: E731
        rng.standard_normal(a.shape), a.dtype)
    k_small = jax.tree.map(randomize, k_small)
    v_small = jax.tree.map(randomize, v_small)

    k_big = model_lib.cache_slot_update(k_big, k_small, 2)
    v_big = model_lib.cache_slot_update(v_big, v_small, 2)
    for big, small in ((k_big, k_small), (v_big, v_small)):
        got = model_lib.cache_slot_read(big, 2)
        jax.tree.map(lambda g, s: np.testing.assert_array_equal(
            np.asarray(g), np.asarray(s)), got, small)
        for other in (0, 1, 3):  # zero-initialized rows stay zero
            jax.tree.map(lambda r: np.testing.assert_array_equal(
                np.asarray(r), 0), model_lib.cache_slot_read(big, other))


def test_cache_slot_update_pytree_aware():
    """Quantized caches are ``{"q", "scale"}`` pytrees per leaf
    (ops/kv_quant.py layout): the slot splice must update every leaf, with
    batch on axis 1."""
    big = {"q": jnp.zeros((2, 4, 8), jnp.int8),
           "scale": jnp.zeros((2, 4, 8), jnp.float32)}
    small = {"q": jnp.ones((2, 1, 8), jnp.int8),
             "scale": jnp.full((2, 1, 8), 0.5, jnp.float32)}
    out = model_lib.cache_slot_update(big, small, 3)
    np.testing.assert_array_equal(np.asarray(out["q"])[:, 3], 1)
    np.testing.assert_array_equal(np.asarray(out["scale"])[:, 3], 0.5)
    np.testing.assert_array_equal(np.asarray(out["q"])[:, :3], 0)
    np.testing.assert_array_equal(np.asarray(out["scale"])[:, :3], 0.0)
    got = model_lib.cache_slot_read(out, 3)
    np.testing.assert_array_equal(np.asarray(got["q"]),
                                  np.asarray(small["q"]))


def test_slot_allocator_free_list(cfg):
    alloc = SlotAllocator(cfg, 3, 8)
    assert alloc.free_slots == 3 and alloc.active_slots == 0
    taken = [alloc.alloc() for _ in range(3)]
    assert sorted(taken) == [0, 1, 2]
    assert alloc.alloc() is None  # exhausted
    assert alloc.active_slots == 3
    alloc.release(taken[1])
    assert alloc.free_slots == 1
    assert alloc.alloc() == taken[1]  # recycled
    with pytest.raises(AssertionError):
        alloc.release(7)  # out of range
    alloc.release(taken[0])
    with pytest.raises(AssertionError):
        alloc.release(taken[0])  # double release


def test_slot_allocator_insert_roundtrip(cfg):
    alloc = SlotAllocator(cfg, 2, 8)
    k1, v1 = model_lib.init_kv_cache(cfg, 1, 8)
    k1 = jax.tree.map(lambda a: jnp.full_like(a, 2.0), k1)
    v1 = jax.tree.map(lambda a: jnp.full_like(a, 3.0), v1)
    alloc.insert(1, k1, v1)
    jax.tree.map(lambda g, s: np.testing.assert_array_equal(
        np.asarray(g), np.asarray(s)),
        model_lib.cache_slot_read(alloc.k_cache, 1), k1)
    jax.tree.map(lambda g, s: np.testing.assert_array_equal(
        np.asarray(g), np.asarray(s)),
        model_lib.cache_slot_read(alloc.v_cache, 1), v1)
    # slot 0 untouched
    jax.tree.map(lambda r: np.testing.assert_array_equal(np.asarray(r), 0),
                 model_lib.cache_slot_read(alloc.k_cache, 0))
