"""Observability spine through the serving stack (docs/observability.md):

- ``GET /metrics?format=prometheus`` serves every subsystem — serving
  counters/summaries, SLO gauges, resilience events — from the one shared
  registry, parseable by a minimal 0.0.4 text parser (round-trip).
- ``GET /metrics`` (JSON) keeps its pre-existing shape.
- ``GET /trace`` returns Chrome trace-event JSON where one request id
  links its ``queued`` → prefill → ``decode`` → ``retire`` spans.
- The structured event log, the trace spans, and the HTTP response all
  carry the same ``request_id`` (end-to-end correlation).
"""

import json
import re
import urllib.request

import jax
import pytest

from megatron_llm_tpu.config import tiny_config
from megatron_llm_tpu.generation.server import (
    GenerationService,
    MegatronServer,
)
from megatron_llm_tpu.models import model as model_lib
from megatron_llm_tpu.obs.logging import EVENT_LOG
from megatron_llm_tpu.tokenizer.tokenizer import NullTokenizer

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})? (\S+)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_prometheus(text):
    """Minimal 0.0.4 parser → (types, samples); asserts on bad lines."""
    types, samples = {}, {}
    for line in text.splitlines():
        if not line.strip() or line.startswith("# HELP"):
            continue
        if line.startswith("# TYPE"):
            _, _, name, mtype = line.split(maxsplit=3)
            types[name] = mtype.strip()
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable exposition line: {line!r}"
        name, labelstr, value = m.groups()
        labels = dict(_LABEL_RE.findall(labelstr)) if labelstr else {}
        samples[(name, frozenset(labels.items()))] = float(value)
    return types, samples


@pytest.fixture(scope="module")
def model():
    cfg = tiny_config(num_layers=1, vocab_size=256,
                      make_vocab_size_divisible_by=8)
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


def _generate(port, prompts, ttg=4):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/api",
        data=json.dumps({"prompts": prompts, "tokens_to_generate": ttg,
                         "no_early_termination": True}).encode(),
        method="PUT", headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=600) as resp:
        return json.loads(resp.read())


def test_prometheus_endpoint_round_trip(model):
    """After real traffic, the text endpoint carries serving counters,
    latency summaries, SLO gauges, and the resilience counter family —
    all from one scrape of the shared registry."""
    cfg, params = model
    server = MegatronServer(cfg, params,
                            NullTokenizer(vocab_size=cfg.vocab_size),
                            max_batch_size=2)
    server.run("127.0.0.1", 0, block=False)
    try:
        _generate(server.port, ["5 9 3", "7 2"], ttg=4)
        url = f"http://127.0.0.1:{server.port}/metrics?format=prometheus"
        with urllib.request.urlopen(url, timeout=60) as resp:
            assert resp.status == 200
            ctype = resp.headers["Content-Type"]
            assert ctype.startswith("text/plain")
            assert "version=0.0.4" in ctype
            text = resp.read().decode()
    finally:
        server.shutdown()

    types, samples = parse_prometheus(text)
    assert types["serving_completed_total"] == "counter"
    assert samples[("serving_completed_total", frozenset())] == 2.0
    assert samples[("serving_submitted_total", frozenset())] == 2.0
    # host-computed reservoir percentiles export as a summary
    assert types["serving_ttft_seconds"] == "summary"
    assert samples[("serving_ttft_seconds_count", frozenset())] == 2.0
    assert ("serving_ttft_seconds",
            frozenset({("quantile", "0.5")})) in samples
    # SLO gauges ride in the same scrape, one row per dimension
    assert types["serving_slo_burn_rate"] == "gauge"
    for dim in ("ttft", "itl", "availability"):
        assert ("serving_slo_compliance",
                frozenset({("slo", dim)})) in samples
    assert samples[("serving_slo_healthy", frozenset())] in (0.0, 1.0)
    # paged KV pool gauges + the COW counter ride the same scrape; after
    # traffic retires, used goes back to 0 but free reflects the pool
    assert types["serving_blocks_free"] == "gauge"
    assert types["serving_blocks_used"] == "gauge"
    assert types["serving_kv_cache_util"] == "gauge"
    assert types["serving_cow_copies_total"] == "counter"
    assert samples[("serving_blocks_free", frozenset())] > 0
    assert samples[("serving_cow_copies_total", frozenset())] == 0.0
    # the resilience collector (metrics.py RESILIENCE_EVENTS) shares it
    assert types["resilience_events_total"] == "counter"


def test_json_metrics_shape_unchanged(model):
    """The original JSON endpoint keeps its keys; Prometheus is opt-in
    via the query parameter, not a format change."""
    cfg, params = model
    server = MegatronServer(cfg, params,
                            NullTokenizer(vocab_size=cfg.vocab_size),
                            max_batch_size=2)
    server.run("127.0.0.1", 0, block=False)
    try:
        _generate(server.port, ["5 9 3"], ttg=3)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/metrics",
                timeout=60) as resp:
            assert resp.headers["Content-Type"] == "application/json"
            snap = json.loads(resp.read())
    finally:
        server.shutdown()
    assert snap["completed"] == 1
    for key in ("submitted", "decode_iterations", "ttft",
                "per_token_latency", "device_idle_frac", "prefix_hit_rate",
                "blocks_free", "blocks_used", "kv_cache_util",
                "cow_copies_total"):
        assert key in snap
    assert snap["ttft"]["count"] == 1  # unified snapshot keys
    assert "p99_s" in snap["ttft"] and "total_count" in snap["ttft"]
    assert snap["slo"]["healthy"] in (True, False)


def test_trace_endpoint_schema_and_request_lifecycle(model):
    """GET /trace after a multi-request run: valid Chrome trace JSON, and
    at least one request id whose queued → prefill → decode → retire
    spans all share that id; engine_step spans carry batch + routing."""
    cfg, params = model
    server = MegatronServer(cfg, params,
                            NullTokenizer(vocab_size=cfg.vocab_size),
                            max_batch_size=2)
    server.run("127.0.0.1", 0, block=False)
    try:
        out = _generate(server.port, ["5 9 3", "7 2", "11 12"], ttg=4)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/trace",
                timeout=60) as resp:
            assert resp.headers["Content-Type"] == "application/json"
            trace = json.loads(resp.read())
    finally:
        server.shutdown()

    assert trace["displayTimeUnit"] == "ms"
    assert "dropped_events" in trace["otherData"]
    events = trace["traceEvents"]
    assert events, "multi-request run produced no trace events"
    for ev in events:
        assert {"name", "ph", "ts", "pid", "tid"} <= set(ev)
        assert ev["ph"] in ("X", "i")
        if ev["ph"] == "X":
            assert ev["dur"] >= 0

    rids = out["request_ids"]
    assert len(rids) == 3 and len(set(rids)) == 3

    def phases(rid):
        return {e["name"] for e in events
                if e.get("args", {}).get("request_id") == rid}

    for rid in rids:
        ph = phases(rid)
        assert "queued" in ph, f"{rid}: {ph}"
        assert any(p == "prefill" or p.startswith("prefill_chunk")
                   for p in ph), f"{rid}: {ph}"
        assert "decode" in ph and "retire" in ph, f"{rid}: {ph}"

    steps = [e for e in events if e["name"] == "engine_step"]
    assert steps, "no per-iteration engine_step spans"
    assert all(e["args"]["batch"] >= 1 for e in steps)
    assert all(e["args"]["route"] in ("fused", "fallback") for e in steps)


def test_request_id_correlates_log_lines_and_spans(model):
    """One id, three views: the HTTP response's request_ids, the
    structured event log's lifecycle lines, and the trace spans."""
    cfg, params = model
    EVENT_LOG.clear()
    svc = GenerationService(cfg, params,
                            NullTokenizer(vocab_size=cfg.vocab_size),
                            max_batch_size=2)
    try:
        status, out = svc.handle({"prompts": ["5 9 3"],
                                  "tokens_to_generate": 3,
                                  "no_early_termination": True})
        assert status == 200
        (rid,) = out["request_ids"]
        lines = EVENT_LOG.recent(request_id=rid)
        seen = [l["event"] for l in lines]
        for event in ("submitted", "first_token", "finished"):
            assert event in seen, f"missing {event} in {seen}"
        finished = next(l for l in lines if l["event"] == "finished")
        assert finished["component"] == "engine"
        assert finished["reason"] in ("length", "eos")
        assert finished["generated"] == 3
        first = next(l for l in lines if l["event"] == "first_token")
        assert first["ttft_s"] > 0

        span_rids = {e.get("args", {}).get("request_id")
                     for e in svc.engine.trace.chrome_trace()["traceEvents"]}
        assert rid in span_rids
    finally:
        svc.close()


def test_no_trace_escape_hatch(model):
    """trace=False (the --no_trace server flag): requests serve normally
    and /trace returns an empty-but-valid document."""
    cfg, params = model
    svc = GenerationService(cfg, params,
                            NullTokenizer(vocab_size=cfg.vocab_size),
                            max_batch_size=2, trace=False)
    try:
        status, out = svc.handle({"prompts": ["5 9"],
                                  "tokens_to_generate": 3,
                                  "no_early_termination": True})
        assert status == 200 and len(out["text"]) == 1
        trace = svc.trace_snapshot()
        assert trace["traceEvents"] == []
        assert not svc.engine.trace.enabled
    finally:
        svc.close()
