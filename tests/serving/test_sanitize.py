"""Runtime-sanitizer integration tests on the serving engine.

Two guarantees from the PR's acceptance bar:

* the fast-path and paged serving loops perform **zero** backend
  compiles after warmup — proven by running a full mixed batch inside
  ``no_recompiles()``;
* a KV block-pool ref-count leak (injected via the chaos harness at
  the slot-release site) is caught by the ledger sanitizer within one
  scheduler iteration and reported with the owning request id.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.analysis.sanitizers import LedgerError, no_recompiles
from megatron_llm_tpu.config import tiny_config
from megatron_llm_tpu.generation import generate_tokens
from megatron_llm_tpu.models import model as model_lib
from megatron_llm_tpu.resilience.chaos import chaos
from megatron_llm_tpu.serving import EngineConfig, ServingEngine


@pytest.fixture(scope="module")
def tiny():
    cfg = tiny_config(num_layers=2, vocab_size=64,
                      make_vocab_size_divisible_by=8)
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


def _engine(cfg, params, **overrides):
    kw = dict(max_batch_size=4, max_seq_len=64, max_queue_size=16,
              idle_wait_s=0.005)
    kw.update(overrides)
    return ServingEngine(cfg, params, EngineConfig(**kw))


def _reference(cfg, params, prompt, max_new):
    total = len(prompt) + max_new
    toks = np.zeros((1, total), np.int32)
    toks[0, :len(prompt)] = prompt
    out = generate_tokens(cfg, params, jnp.asarray(toks),
                          jnp.asarray([len(prompt)], jnp.int32),
                          eos_id=-1, use_eos_stop=False)
    return np.asarray(out.tokens)[0].tolist()


def _mixed_batch(cfg):
    rng = np.random.default_rng(31)
    prompts = [rng.integers(1, cfg.vocab_size, n).tolist()
               for n in (3, 17, 30, 9)]
    max_news = [12, 7, 10, 5]
    return prompts, max_news


def _run(engine, prompts, max_news):
    handles = [engine.submit(p, max_new_tokens=n, use_eos_stop=False)
               for p, n in zip(prompts, max_news)]
    return [h.result(timeout=600) for h in handles]


def _assert_zero_recompiles_after_warmup(cfg, params, **overrides):
    prompts, max_news = _mixed_batch(cfg)
    engine = _engine(cfg, params, **overrides).start()
    try:
        # warmup twice: the second pass exercises the prefix-cache hit
        # path (identical prompts), so its gather executable is warm too
        _run(engine, prompts, max_news)
        _run(engine, prompts, max_news)
        with no_recompiles():
            results = _run(engine, prompts, max_news)
    finally:
        engine.shutdown()
    for p, n, r in zip(prompts, max_news, results):
        assert r.finish_reason == "length"
        assert r.tokens == _reference(cfg, params, p, n)


def test_fastpath_zero_recompiles_after_warmup(tiny):
    """Pipelined decode + chunked prefill: steady state never retraces."""
    cfg, params = tiny
    _assert_zero_recompiles_after_warmup(
        cfg, params, pipeline_decode=True, prefill_chunk=16)


def test_paged_zero_recompiles_after_warmup(tiny):
    """Small-block paged KV with decode-time growth crossing block
    boundaries: steady state never retraces."""
    cfg, params = tiny
    _assert_zero_recompiles_after_warmup(cfg, params, kv_block_size=8)


def test_sanitized_engine_runs_clean(tiny):
    """EngineConfig.sanitize audits the ledger every scheduler iteration
    and a healthy run produces no report."""
    cfg, params = tiny
    prompts, max_news = _mixed_batch(cfg)
    engine = _engine(cfg, params, kv_block_size=8, sanitize=True).start()
    try:
        results = _run(engine, prompts, max_news)
        assert all(r.finish_reason == "length" for r in results)
        assert engine._sanitizer is not None
        assert engine._sanitizer.checks > 0
        engine.drain(timeout=60)
        assert engine.sanitizer_report == []
        assert engine._scheduler_error is None
    finally:
        engine.shutdown()


@pytest.mark.chaos
def test_chaos_injected_block_leak_is_reported(tiny):
    """Drop one decref on the floor at slot release (chaos site
    ``slots-release``): the ledger sanitizer must fail the engine loudly
    within one iteration and name the leaked block's last owner."""
    cfg, params = tiny
    engine = _engine(cfg, params, kv_block_size=8, prefix_cache_blocks=0,
                     sanitize=True).start()
    try:
        # a clean request first: the sanitizer has passing checks and a
        # recorded owner map before the fault fires
        ok = engine.submit([5, 9, 3, 7], max_new_tokens=4,
                           use_eos_stop=False).result(timeout=600)
        assert ok.finish_reason == "length"
        assert engine._sanitizer.checks > 0

        chaos().leak_kv_blocks("slots-release")
        h = engine.submit([2, 4, 6, 8, 10], max_new_tokens=4,
                          use_eos_stop=False)
        rid = h.rid
        h.result(timeout=600)  # completes; its release leaks one ref

        deadline = time.monotonic() + 60
        while engine._scheduler_error is None and time.monotonic() < deadline:
            time.sleep(0.01)
        err = engine._scheduler_error
        assert isinstance(err, LedgerError), f"no ledger failure: {err!r}"
        assert "leaked" in str(err)

        report = engine._sanitizer.leak_report(engine)
        assert report, "leak_report should name the leaked block"
        assert any(rid in leak["last_owners"] for leak in report), \
            f"{rid} missing from {report}"
        assert any(("kv_leak", "slots-release") == ev[:2]
                   for ev in chaos().events)
    finally:
        chaos().reset()
        engine.shutdown()


_REP_PROMPTS = [[5, 9, 3, 5, 9, 3, 5, 9, 3, 5, 9],
                [7, 7, 7, 7, 7, 7, 7],
                [4, 8, 2, 4, 8, 2, 4, 8],
                [11, 6, 11, 6, 11, 6, 11]]


def test_spec_zero_recompiles_after_warmup(tiny):
    """Speculative serving in steady state never retraces: the verify
    executable has one fixed [slots, W] shape whatever mix of draft
    lengths the slots carry (short drafts pad into the window), and the
    accept/rollback bookkeeping is pure host arithmetic.  Repetitive
    prompts so the drafter really engages — asserted, else this test
    would vouch for a path it never ran."""
    cfg, params = tiny
    engine = _engine(cfg, params, kv_block_size=8, spec_draft_len=3).start()
    try:
        # two warmup passes: prefill/decode/verify executables plus the
        # prefix-cache hit path (identical prompts) all compile here
        _run(engine, _REP_PROMPTS, [20] * 4)
        _run(engine, _REP_PROMPTS, [20] * 4)
        with no_recompiles():
            results = _run(engine, _REP_PROMPTS, [20] * 4)
    finally:
        engine.shutdown()
    for p, r in zip(_REP_PROMPTS, results):
        assert r.finish_reason == "length"
        assert r.tokens == _reference(cfg, params, p, 20)
    assert engine.metrics.snapshot()["spec_steps"] > 0


@pytest.mark.chaos
def test_chaos_block_leak_reported_under_spec(tiny):
    """The ledger sanitizer keeps its one-iteration detection bar with
    speculation on: verify steps allocate draft rows through the same
    append path, and a dropped decref at slot release is still caught
    and attributed."""
    cfg, params = tiny
    engine = _engine(cfg, params, kv_block_size=8, prefix_cache_blocks=0,
                     spec_draft_len=3, sanitize=True).start()
    try:
        ok = engine.submit(_REP_PROMPTS[1], max_new_tokens=20,
                           use_eos_stop=False).result(timeout=600)
        assert ok.finish_reason == "length"
        assert engine.metrics.snapshot()["spec_steps"] > 0
        assert engine._sanitizer.checks > 0

        chaos().leak_kv_blocks("slots-release")
        h = engine.submit(_REP_PROMPTS[2], max_new_tokens=20,
                          use_eos_stop=False)
        rid = h.rid
        h.result(timeout=600)

        deadline = time.monotonic() + 60
        while engine._scheduler_error is None and time.monotonic() < deadline:
            time.sleep(0.01)
        err = engine._scheduler_error
        assert isinstance(err, LedgerError), f"no ledger failure: {err!r}"
        report = engine._sanitizer.leak_report(engine)
        assert any(rid in leak["last_owners"] for leak in report), \
            f"{rid} missing from {report}"
    finally:
        chaos().reset()
        engine.shutdown()


# ---------------------------------------------------------------------------
# Resident draft model + tree verification (round 15)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_draft():
    """A draft even tinier than the target: one layer, quarter hidden."""
    cfg = tiny_config(num_layers=1, hidden_size=32, vocab_size=64,
                      make_vocab_size_divisible_by=8)
    params = model_lib.init_params(jax.random.key(1), cfg)
    return cfg, params


def _tree_engine(tiny, draft, **overrides):
    cfg, params = tiny
    dcfg, dparams = draft
    kw = dict(max_batch_size=4, max_seq_len=64, max_queue_size=16,
              idle_wait_s=0.005, kv_block_size=8, spec_draft_len=3)
    kw.update(overrides)
    return ServingEngine(cfg, params, EngineConfig(**kw),
                         draft_cfg=dcfg, draft_params=dparams)


def test_tree_spec_trajectories_bitwise_across_modes(tiny, tiny_draft):
    """Resident-draft tree speculation end to end: greedy trajectories
    equal the non-speculative generate_tokens reference in pipelined AND
    sync decode, a sampled rider produces the identical token stream in
    both modes (its seed/counter bookkeeping is untouched by tree
    commits), and the spec counters attribute the steps to the model
    drafter."""
    cfg, params = tiny
    prompts, max_news = _mixed_batch(cfg)
    rider_tokens = {}
    for pipelined in (True, False):
        engine = _tree_engine(tiny, tiny_draft,
                              pipeline_decode=pipelined).start()
        try:
            results = _run(engine, prompts, max_news)
            h2 = engine.submit(prompts[0], max_new_tokens=8,
                               temperature=0.9, top_k=5, seed=7,
                               use_eos_stop=False)
            rider_tokens[pipelined] = h2.result(timeout=600).tokens
        finally:
            engine.shutdown()
        assert engine._scheduler_error is None, engine._scheduler_error
        for p, n, r in zip(prompts, max_news, results):
            assert r.tokens == _reference(cfg, params, p, n)
        snap = engine.metrics.snapshot()
        assert snap["spec_steps"] > 0
        assert "model" in snap["spec_by_source"]
    assert len(rider_tokens[True]) == len(prompts[0]) + 8
    assert rider_tokens[True] == rider_tokens[False]


def test_tree_spec_zero_recompiles_after_warmup(tiny, tiny_draft):
    """With a draft model resident, steady state still never retraces:
    draft prefill/absorb/expand and the tree verify all have one fixed
    shape each (trees pad to the static node budget), so the third pass
    runs entirely on warm executables.  Random prompts — the model
    drafter engages on ANY traffic, no repetition needed."""
    cfg, params = tiny
    prompts, max_news = _mixed_batch(cfg)
    engine = _tree_engine(tiny, tiny_draft).start()
    try:
        _run(engine, prompts, max_news)
        _run(engine, prompts, max_news)
        with no_recompiles():
            results = _run(engine, prompts, max_news)
    finally:
        engine.shutdown()
    for p, n, r in zip(prompts, max_news, results):
        assert r.finish_reason == "length"
        assert r.tokens == _reference(cfg, params, p, n)
    assert engine.metrics.snapshot()["spec_steps"] > 0
    assert "model" in engine.metrics.snapshot()["spec_by_source"]


def test_tree_spec_block_boundary_ledger_balanced(tiny, tiny_draft):
    """Trees crossing KV block boundaries under the ledger sanitizer:
    kv_block_size=8 with draft_len=3 means accepted paths regularly
    straddle block edges (target AND shadow draft pool), and the
    per-iteration ledger audit plus the drain report must stay clean."""
    cfg, params = tiny
    prompts, max_news = _mixed_batch(cfg)
    engine = _tree_engine(tiny, tiny_draft, sanitize=True).start()
    try:
        results = _run(engine, prompts, max_news)
        assert all(r.finish_reason == "length" for r in results)
        assert engine._sanitizer is not None
        assert engine._sanitizer.checks > 0
        engine.drain(timeout=60)
        assert engine.sanitizer_report == []
        assert engine._scheduler_error is None
    finally:
        engine.shutdown()
    for p, n, r in zip(prompts, max_news, results):
        assert r.tokens == _reference(cfg, params, p, n)


def test_tree_spec_eos_mid_tree(tiny):
    """EOS landing in the MIDDLE of an accepted tree path: a self-draft
    (draft == target) accepts whole chains, so the EOS token is committed
    inside a multi-token burst — generation must stop AT the EOS token
    with the exact reference prefix, and the tokens drafted past it must
    never surface."""
    cfg, params = tiny
    prompt = [5, 9, 3]
    ref = _reference(cfg, params, prompt, 8)
    gen = ref[len(prompt):]
    eos = gen[2]  # a token the greedy rollout actually emits
    engine = _tree_engine(tiny, (cfg, params)).start()
    try:
        r = engine.submit(prompt, max_new_tokens=8,
                          eos_id=eos).result(timeout=600)
    finally:
        engine.shutdown()
    assert engine._scheduler_error is None, engine._scheduler_error
    assert r.finish_reason == "eos"
    stop = gen.index(eos) + 1
    assert r.tokens == ref[:len(prompt) + stop]
    assert engine.metrics.snapshot()["spec_steps"] > 0


def test_tree_spec_perfect_draft_acceptance(tiny):
    """Self-draft (draft == target) is the acceptance upper bound: the
    main chain always matches target argmax, so the accepted-per-proposed
    rate must be high while trajectories stay bitwise."""
    cfg, params = tiny
    prompts, max_news = _mixed_batch(cfg)
    engine = _tree_engine(tiny, (cfg, params)).start()
    try:
        results = _run(engine, prompts, max_news)
    finally:
        engine.shutdown()
    assert engine._scheduler_error is None, engine._scheduler_error
    for p, n, r in zip(prompts, max_news, results):
        assert r.tokens == _reference(cfg, params, p, n)
    snap = engine.metrics.snapshot()
    rate = snap["spec_accepted"] / max(1, snap["spec_proposed"])
    assert rate > 0.5, snap


def test_tree_spec_forced_hedge_compaction(tiny, monkeypatch):
    """Force the accept walk onto the HEDGE branch: patch the draft's
    chain heads so the main chain carries a deliberately wrong token and
    the hedge seat carries the draft's true head.  Acceptance then lands
    on a node whose index differs from its depth, exercising the
    cache_move_rows re-pack — trajectories must stay bitwise through it."""
    from megatron_llm_tpu.serving import engine as engine_mod

    cfg, params = tiny
    prompts, max_news = _mixed_batch(cfg)
    real_absorb = engine_mod.ServingEngine._draft_absorb
    hedge_hits = {"n": 0}

    def fake_absorb(self, plans, tables):
        heads = real_absorb(self, plans, tables)
        out = {}
        for slot, toks in heads.items():
            wrong = (int(toks[0]) + 1) % cfg.vocab_size
            out[slot] = [wrong, int(toks[0])]
            hedge_hits["n"] += 1
        return out

    monkeypatch.setattr(engine_mod.ServingEngine, "_draft_absorb",
                        fake_absorb)
    engine = _tree_engine(tiny, (cfg, params)).start()
    try:
        results = _run(engine, prompts, max_news)
    finally:
        engine.shutdown()
    assert engine._scheduler_error is None, engine._scheduler_error
    for p, n, r in zip(prompts, max_news, results):
        assert r.tokens == _reference(cfg, params, p, n)
    assert hedge_hits["n"] > 0
    assert engine.metrics.snapshot()["spec_accepted"] > 0
