"""Serving decode fast-path tests (CPU, tiny model).

Covers the pipelined scheduler (one-step decode pipeline with lagged
retirement), chunked prefill admission, the condition-variable wakeups,
and the device/host metrics breakdown.  The load-bearing invariant is
the same bar the engine met at birth: greedy requests must be bitwise
identical to the one-shot ``generate_tokens`` trajectory — pipelined or
not, chunked or not — and a lagged-retirement slot must never leak its
masked speculative token into results or streaming callbacks.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.config import tiny_config
from megatron_llm_tpu.generation import generate_tokens
from megatron_llm_tpu.models import model as model_lib
from megatron_llm_tpu.serving import EngineConfig, ServingEngine


@pytest.fixture(scope="module")
def tiny():
    cfg = tiny_config(num_layers=2, vocab_size=64,
                      make_vocab_size_divisible_by=8)
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


def _engine(cfg, params, **overrides):
    kw = dict(max_batch_size=4, max_seq_len=64, max_queue_size=16)
    kw.update(overrides)
    return ServingEngine(cfg, params, EngineConfig(**kw))


def _reference(cfg, params, prompt, max_new):
    total = len(prompt) + max_new
    toks = np.zeros((1, total), np.int32)
    toks[0, :len(prompt)] = prompt
    out = generate_tokens(cfg, params, jnp.asarray(toks),
                          jnp.asarray([len(prompt)], jnp.int32),
                          eos_id=-1, use_eos_stop=False)
    return np.asarray(out.tokens)[0].tolist()


def _run_batch(engine, prompts, max_news):
    handles = []
    try:
        for p, n in zip(prompts, max_news):
            handles.append(engine.submit(p, max_new_tokens=n,
                                         use_eos_stop=False))
            time.sleep(0.002)
        return [h.result(timeout=600) for h in handles]
    finally:
        engine.shutdown()


@pytest.mark.parametrize("pipeline", [True, False],
                         ids=["pipelined", "sync"])
def test_decode_matches_one_shot(tiny, pipeline):
    """Bitwise one-shot equivalence for both scheduler modes; ragged
    budgets force staggered lagged retirements mid-batch."""
    cfg, params = tiny
    rng = np.random.default_rng(1)
    prompts = [rng.integers(1, cfg.vocab_size,
                            int(rng.integers(3, 11))).tolist()
               for _ in range(6)]
    max_news = [int(rng.integers(4, 14)) for _ in range(6)]
    engine = _engine(cfg, params, pipeline_decode=pipeline).start()
    results = _run_batch(engine, prompts, max_news)
    for p, n, r in zip(prompts, max_news, results):
        assert r.finish_reason == "length"
        assert r.tokens == _reference(cfg, params, p, n)
    assert engine.metrics.snapshot()["max_decode_batch"] >= 2


@pytest.fixture(scope="module")
def tiny_int8(tiny):
    """The tiny model fully int8-resident: quantized weights + int8 KV.
    max_position_embeddings=128 keeps the fused kernel's block_k >= 128
    constraint satisfiable when tests force the fused path on CPU."""
    import dataclasses

    from megatron_llm_tpu.ops.quant import quantize_params

    cfg, params = tiny
    cfg_q = dataclasses.replace(cfg, kv_cache_quant="int8")
    return cfg_q, quantize_params(params)


def test_int8_decode_matches_one_shot_pipelined(tiny_int8):
    """Bitwise one-shot equivalence for a fully int8 model (int8 weights
    + int8 KV dict cache) under the pipelined scheduler, and the
    fused/fallback routing counters: on CPU the static eligibility
    predicate rejects (platform), so every step must count as fallback."""
    cfg, params = tiny_int8
    rng = np.random.default_rng(7)
    prompts = [rng.integers(1, cfg.vocab_size,
                            int(rng.integers(3, 11))).tolist()
               for _ in range(5)]
    max_news = [int(rng.integers(4, 12)) for _ in range(5)]
    engine = _engine(cfg, params, pipeline_decode=True).start()
    results = _run_batch(engine, prompts, max_news)
    for p, n, r in zip(prompts, max_news, results):
        assert r.finish_reason == "length"
        assert r.tokens == _reference(cfg, params, p, n)
    snap = engine.metrics.snapshot()
    assert snap["max_decode_batch"] >= 2
    assert snap["fused_steps"] == 0
    # counts DISPATCHED steps: may exceed committed decode_iterations by
    # the pipeline's final speculative step, never undercount them
    assert snap["fallback_steps"] >= snap["decode_iterations"] > 0


def test_int8_slot_batch_routes_through_fused_kernel(tiny_int8):
    """The serving slot batch really runs the int8 fused kernel: with
    eligibility forced (CPU would reject on platform alone; the kernel
    itself runs in interpret mode), a 4-slot pipelined batch must commit
    the same tokens as a 1-slot engine — the kernel's rows are
    independent, so slot batching may not perturb any trajectory — and
    the fused_steps counter must attribute the iterations."""
    import megatron_llm_tpu.kernels.decode_step as ds

    cfg, params = tiny_int8
    rng = np.random.default_rng(8)
    prompts = [rng.integers(1, cfg.vocab_size,
                            int(rng.integers(3, 9))).tolist()
               for _ in range(4)]
    max_news = [int(rng.integers(4, 10)) for _ in range(4)]
    orig_eligible = ds.fused_paged_decode_eligible
    try:
        # force the fused paged path (CPU would reject on platform alone;
        # fused_decode_step_paged defaults to interpret mode off-TPU);
        # kv_block_size keeps the interpret-mode attend grid small
        ds.fused_paged_decode_eligible = lambda *a, **k: True

        # one-slot engine: each request decodes alone through the fused
        # kernel — the committed-trajectory reference
        single = []
        engine = _engine(cfg, params, max_batch_size=1, max_seq_len=128,
                         kv_block_size=32, pipeline_decode=True).start()
        try:
            for p, n in zip(prompts, max_news):
                single.append(engine.submit(
                    p, max_new_tokens=n,
                    use_eos_stop=False).result(timeout=600))
        finally:
            engine.shutdown()
        engine = _engine(cfg, params, max_batch_size=4, max_seq_len=128,
                         kv_block_size=32, pipeline_decode=True).start()
        batched = _run_batch(engine, prompts, max_news)
        snap = engine.metrics.snapshot()
    finally:
        ds.fused_paged_decode_eligible = orig_eligible
    for i, (s, b) in enumerate(zip(single, batched)):
        assert b.finish_reason == "length"
        assert b.tokens == s.tokens, f"slot batching perturbed request {i}"
    assert snap["fused_steps"] >= snap["decode_iterations"] > 0
    assert snap["fallback_steps"] == 0


def test_chunked_prefill_matches_one_shot(tiny):
    """Chunked admission (prefill_chunk smaller than most prompts) must
    not change a single committed token, including for prompts shorter
    than one chunk and prompts arriving mid-decode."""
    cfg, params = tiny
    rng = np.random.default_rng(2)
    prompts = [rng.integers(1, cfg.vocab_size,
                            int(rng.integers(2, 25))).tolist()
               for _ in range(6)]
    max_news = [int(rng.integers(4, 12)) for _ in range(6)]
    engine = _engine(cfg, params, prefill_chunk=4).start()
    results = _run_batch(engine, prompts, max_news)
    for p, n, r in zip(prompts, max_news, results):
        assert r.finish_reason == "length"
        assert r.tokens == _reference(cfg, params, p, n)
    snap = engine.metrics.snapshot()
    assert snap["prefills"] == 6
    # chunked admission really ran chunk-at-a-time: more chunks than
    # prefills because prompts longer than one chunk took several
    expected_chunks = sum(-(-min(-(-len(p) // 4) * 4, 64) // 4)
                          for p in prompts)
    assert snap["prefill_chunks"] == expected_chunks
    assert snap["max_decode_batch"] >= 2


def test_long_prompt_admission_interleaves_with_decode(tiny):
    """A long prompt arriving while another request is decoding must be
    admitted chunk-by-chunk without corrupting the active stream."""
    cfg, params = tiny
    short = [5, 9, 3]
    long = list(range(1, 33))  # 32 tokens = 8 chunks of 4
    engine = _engine(cfg, params, prefill_chunk=4).start()
    try:
        h1 = engine.submit(short, max_new_tokens=20, use_eos_stop=False)
        time.sleep(0.05)  # let decode get going
        h2 = engine.submit(long, max_new_tokens=6, use_eos_stop=False)
        r1 = h1.result(timeout=600)
        r2 = h2.result(timeout=600)
    finally:
        engine.shutdown()
    assert r1.tokens == _reference(cfg, params, short, 20)
    assert r2.tokens == _reference(cfg, params, long, 6)


def test_lagged_retirement_never_leaks_speculative_token(tiny):
    """In pipelined mode the step after a slot's last committed token has
    already sampled one speculative token for it.  Neither the result
    tokens nor the streaming callback may ever see it — for any request,
    across staggered retirements."""
    cfg, params = tiny
    rng = np.random.default_rng(3)
    prompts = [rng.integers(1, cfg.vocab_size, 4).tolist()
               for _ in range(4)]
    max_news = [3, 5, 8, 11]  # retire at different iterations
    streamed = {i: [] for i in range(4)}
    engine = _engine(cfg, params, pipeline_decode=True).start()
    try:
        handles = []
        for i, (p, n) in enumerate(zip(prompts, max_news)):
            handles.append(engine.submit(
                p, max_new_tokens=n, use_eos_stop=False,
                on_token=streamed[i].append))
        results = [h.result(timeout=600) for h in handles]
        # the engine keeps running (other slots still active) after each
        # early retirement — exactly when a leak would happen
    finally:
        engine.shutdown()
    for i, (p, n, r) in enumerate(zip(prompts, max_news, results)):
        ref = _reference(cfg, params, p, n)
        assert r.tokens == ref, f"request {i} trajectory diverged"
        # result holds EXACTLY max_new generated tokens: no speculative
        # extra, and the stream saw the same tokens in the same order
        assert len(r.tokens) == len(p) + n
        assert streamed[i] == ref[len(p):], (
            f"request {i} streamed tokens diverged from committed ones")


def test_cancelled_slot_discards_inflight_token(tiny):
    """Cancellation while a pipelined step is in flight: the cancelled
    request's stream must stop at the committed prefix (no token from the
    already-dispatched step) and keep a valid one-shot prefix."""
    cfg, params = tiny
    prompt = [7, 3, 11, 2]
    got = []
    hold = threading.Event()

    def on_token(t):
        got.append(t)
        if len(got) == 3:
            hold.set()
        time.sleep(0.01)  # throttle so cancel lands mid-generation

    engine = _engine(cfg, params).start()
    try:
        h = engine.submit(prompt, max_new_tokens=50, use_eos_stop=False,
                          on_token=on_token)
        assert hold.wait(timeout=600)
        h.cancel()
        r = h.result(timeout=600)
    finally:
        engine.shutdown()
    assert r.finish_reason == "cancelled"
    ref = _reference(cfg, params, prompt, 50)
    n = len(r.tokens) - len(prompt)
    assert 0 < n < 50
    assert r.tokens == ref[:len(prompt) + n]  # a prefix, nothing bolted on
    assert got == r.tokens[len(prompt):]


def test_metrics_step_breakdown(tiny):
    """The device/host breakdown must show the pipeline overlapping host
    work: a pipelined run never observes device idle between steps (a
    step is always in flight), a sync run always does."""
    cfg, params = tiny
    prompts = [[3, 5, 7], [2, 4, 6]]

    def run(pipeline):
        engine = _engine(cfg, params, pipeline_decode=pipeline).start()
        _run_batch(engine, prompts, [16, 16])
        return engine.metrics.snapshot()

    sync_snap = run(False)
    pipe_snap = run(True)
    for snap in (sync_snap, pipe_snap):
        assert snap["device_step_time"]["count"] > 0
        assert snap["sched_host_time"]["count"] > 0
        assert snap["device_step_time"]["mean_s"] > 0.0
    assert sync_snap["device_idle_frac"] > 0.0
    assert pipe_snap["device_idle_frac"] == 0.0
    assert pipe_snap["device_idle_frac"] < sync_snap["device_idle_frac"]


def test_idle_wakeup_is_not_sleep_bound(tiny):
    """With condition-variable wakeups an idle engine must pick up a new
    request immediately even when idle_wait_s is huge."""
    cfg, params = tiny
    engine = _engine(cfg, params, idle_wait_s=30.0).start()
    try:
        # first submission compiles the forwards; do it before timing
        engine.submit([1, 2, 3], max_new_tokens=2,
                      use_eos_stop=False).result(timeout=600)
        time.sleep(0.1)  # let the scheduler park itself in the idle wait
        t0 = time.perf_counter()
        engine.submit([4, 5, 6], max_new_tokens=2,
                      use_eos_stop=False).result(timeout=600)
        dt = time.perf_counter() - t0
    finally:
        engine.shutdown()
    assert dt < 5.0  # << idle_wait_s: woken by notify, not by timeout


def test_drain_wakes_without_polling(tiny):
    """drain() must return promptly once the last request finishes even
    with a huge idle_wait_s (it is notified, not sleep-polled)."""
    cfg, params = tiny
    engine = _engine(cfg, params, idle_wait_s=30.0).start()
    try:
        h = engine.submit([1, 2, 3], max_new_tokens=4, use_eos_stop=False)
        assert engine.drain(timeout=600.0)
        assert h.done()
    finally:
        engine.shutdown()


def test_pause_resume_with_pipeline(tiny):
    """pause() flushes the in-flight step; resume() continues the exact
    trajectory (the post-pause dispatch re-feeds host-known tokens)."""
    cfg, params = tiny
    prompt = [9, 1, 4]
    engine = _engine(cfg, params).start()
    try:
        seen = threading.Event()
        h = engine.submit(prompt, max_new_tokens=16, use_eos_stop=False,
                          on_token=lambda _t: seen.set())
        assert seen.wait(timeout=600)
        engine.pause()
        time.sleep(0.05)
        engine.resume()
        r = h.result(timeout=600)
    finally:
        engine.shutdown()
    assert r.tokens == _reference(cfg, params, prompt, 16)
