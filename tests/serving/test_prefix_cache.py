"""Automatic prefix-cache tests (CPU, tiny model).

Two layers:

- **unit** — the radix trie over pool block ids: offers adopt a retiring
  slot's blocks by ref bump (zero K/V copies), matches hand the ids back
  as a pinned lease, LRU eviction respects the block budget and returns
  pool refs, ref-count pinning protects a live request's blocks under
  pressure, and a released lease becomes evictable; plus
  ``models/model.py:cache_slot_copy`` row surgery directly.
- **engine** — the load-bearing invariant: a prefix-HIT admission must
  commit bitwise the same tokens as the one-shot ``generate_tokens``
  trajectory (the same bar every fast-path PR met), whole-prompt and
  chunked, fp32 and fully-int8, with the hit actually counted and the
  pure-hit path performing ZERO copy-on-write copies.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.config import tiny_config
from megatron_llm_tpu.generation import generate_tokens
from megatron_llm_tpu.models import model as model_lib
from megatron_llm_tpu.serving import (
    EngineConfig,
    PrefixCache,
    ServingEngine,
    ServingMetrics,
)


@pytest.fixture(scope="module")
def tiny():
    cfg = tiny_config(num_layers=2, vocab_size=64,
                      make_vocab_size_divisible_by=8)
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


@pytest.fixture(scope="module")
def tiny_int8(tiny):
    from megatron_llm_tpu.ops.quant import quantize_params

    cfg, params = tiny
    cfg_q = dataclasses.replace(cfg, kv_cache_quant="int8")
    return cfg_q, quantize_params(params)


def _rand_like(tree, seed):
    """Random-content cache of the same structure/dtypes: int8 leaves get
    random bytes, float leaves uniform values — recognizable rows so row
    surgery mistakes show up as value mismatches."""
    leaves, treedef = jax.tree.flatten(tree)
    out = []
    for i, a in enumerate(leaves):
        k = jax.random.fold_in(jax.random.key(seed), i)
        if a.dtype == jnp.int8:
            out.append(jax.random.randint(k, a.shape, -127, 128,
                                          jnp.int32).astype(jnp.int8))
        else:
            out.append(jax.random.uniform(k, a.shape,
                                          jnp.float32).astype(a.dtype))
    return jax.tree.unflatten(treedef, out)


def _rows(cache, slot, start, stop):
    """Host copy of sequence rows [start, stop) of batch row ``slot``
    for every leaf (seq axis 3)."""
    return [np.asarray(a[:, slot:slot + 1, :, start:stop])
            for a in jax.tree.leaves(cache)]


# ---------------------------------------------------------------------------
# cache_slot_copy
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("quant", ["fp32", "int8"])
def test_cache_slot_copy_moves_exact_rows(tiny, quant):
    cfg, _ = tiny
    if quant == "int8":
        cfg = dataclasses.replace(cfg, kv_cache_quant="int8")
    src, _ = model_lib.init_kv_cache(cfg, 2, 16)
    src = _rand_like(src, seed=1)
    dst, _ = model_lib.init_kv_cache(cfg, 3, 32)
    out = model_lib.cache_slot_copy(dst, src, dst_slot=2, dst_pos=8,
                                    src_slot=1, src_pos=4, length=8)
    for got, want in zip(_rows(out, 2, 8, 16), _rows(src, 1, 4, 12)):
        np.testing.assert_array_equal(got, want)
    # rows outside the window stay zero-initialized
    for leaf in jax.tree.leaves(out):
        assert not np.asarray(leaf[:, 2:3, :, :8]).any()
        assert not np.asarray(leaf[:, :2]).any()


# ---------------------------------------------------------------------------
# Trie units (pool block ids, no engine)
# ---------------------------------------------------------------------------

from megatron_llm_tpu.serving.block_pool import BlockPool  # noqa: E402


def _mk_cache(cfg, *, block=4, budget=8, max_seq=32, n_blocks=32,
              metrics=None):
    pool = BlockPool(cfg, n_blocks, block)
    return pool, PrefixCache(cfg, pool=pool, max_blocks=budget,
                             max_seq_len=max_seq, metrics=metrics)


def _slot_table(pool, n):
    """Emulate an admitted slot: allocate ``n`` blocks (the slot holds
    one pool ref each, as SlotAllocator.insert would)."""
    assert pool.reserve(n)
    return [pool.alloc_reserved() for _ in range(n)]


def _retire(pool, table):
    """Emulate slot release after an offer: the slot's own refs drop;
    only refs the trie (or another sharer) took keep blocks alive."""
    for bid in table:
        pool.decref(bid)


@pytest.mark.parametrize("quant", ["fp32", "int8"])
def test_offer_match_is_zero_copy_ref_bump(tiny, quant):
    """offer() adopts a retiring slot's blocks by pool incref — no K/V
    bytes move (fp32 and int8 pools alike) — and a later match hands the
    SAME pool block ids back as a pinned lease."""
    cfg, _ = tiny
    if quant == "int8":
        cfg = dataclasses.replace(cfg, kv_cache_quant="int8")
    m = ServingMetrics()
    pool, cache = _mk_cache(cfg, metrics=m)
    tokens = list(range(1, 11))  # 10 tokens -> 2 full blocks of 4
    table = _slot_table(pool, 3)  # ceil(10/4): 2 full + boundary block
    assert cache.offer(tokens, table) == 2
    assert cache.blocks == 2
    assert all(pool.ref(b) == 2 for b in table[:2])  # slot + trie
    _retire(pool, table)
    assert all(pool.ref(b) == 1 for b in table[:2])  # trie keeps them
    assert pool.used_blocks == 2                     # boundary block freed

    lease = cache.match_and_acquire(tokens)
    assert lease is not None and lease.tokens == 8
    assert lease.bids == table[:2]   # the very same pool blocks
    assert pool.cow_copies == 0      # adoption + match moved zero bytes
    cache.release(lease)
    snap = m.snapshot()
    assert snap["prefix_hits"] == 1
    assert snap["prefix_hit_tokens"]["mean"] == 8.0


def test_match_is_strictly_shorter_than_prompt(tiny):
    """A fully-cached prompt must still leave >= 1 token for the suffix
    prefill: an exactly-2-block prompt matches only 1 block."""
    cfg, _ = tiny
    pool, cache = _mk_cache(cfg)
    tokens = list(range(1, 9))  # exactly 2 blocks
    table = _slot_table(pool, 2)
    cache.offer(tokens, table)
    _retire(pool, table)
    lease = cache.match_and_acquire(tokens)
    assert lease is not None and lease.tokens == 4
    cache.release(lease)
    # shorter than one block: no usable prefix at all
    assert cache.match_and_acquire(tokens[:4]) is None


def test_match_miss_diverging_block(tiny):
    cfg, _ = tiny
    m = ServingMetrics()
    pool, cache = _mk_cache(cfg, metrics=m)
    table = _slot_table(pool, 2)
    cache.offer([1, 2, 3, 4, 5, 6, 7, 8], table)
    _retire(pool, table)
    assert cache.match_and_acquire([9, 9, 9, 9, 5, 6]) is None
    # divergence in the SECOND block still matches the first
    lease = cache.match_and_acquire([1, 2, 3, 4, 9, 9, 9, 9, 1])
    assert lease is not None and lease.tokens == 4
    cache.release(lease)
    assert m.snapshot()["prefix_misses"] == 1


def test_lru_eviction_under_budget_pressure(tiny):
    """Budget 2: offering a third distinct prefix evicts the least
    recently USED block (A was touched after B's insert, so B goes) —
    and eviction returns the block's pool ref to the free list."""
    cfg, _ = tiny
    m = ServingMetrics()
    pool, cache = _mk_cache(cfg, budget=2, metrics=m)
    A, B, C = [10] * 5, [20 + i for i in range(5)], [30] * 5
    for toks in (A, B):
        t = _slot_table(pool, 2)
        cache.offer(toks, t)
        _retire(pool, t)
    cache.release(cache.match_and_acquire(A))  # LRU-touch A
    t = _slot_table(pool, 2)
    cache.offer(C, t)
    _retire(pool, t)
    assert cache.blocks == 2
    assert pool.used_blocks == 2               # B's block is FREE again
    assert cache.match_and_acquire(B) is None          # evicted
    lease = cache.match_and_acquire(A)                 # survived
    assert lease is not None
    cache.release(lease)
    assert cache.match_and_acquire(C) is not None      # newest
    assert m.snapshot()["prefix_evicted_blocks"] == 1


def test_ref_pinning_blocks_eviction_until_release(tiny):
    """A block pinned by a live lease must survive any budget pressure;
    once released it becomes the eviction victim."""
    cfg, _ = tiny
    pool, cache = _mk_cache(cfg, budget=1)
    A, B = [1, 2, 3, 4, 5], [6, 7, 8, 9, 10]
    t = _slot_table(pool, 2)
    cache.offer(A, t)
    _retire(pool, t)
    lease = cache.match_and_acquire(A)   # pin A (a live request)
    assert lease is not None
    t = _slot_table(pool, 2)
    cache.offer(B, t)                    # over budget; A is pinned
    _retire(pool, t)
    assert cache.match_and_acquire(B) is None   # B was the only victim
    held = cache.match_and_acquire(A)
    assert held is not None                     # A survived the pressure
    cache.release(held)
    cache.release(lease)                 # unpin: A is now fair game
    t = _slot_table(pool, 2)
    cache.offer(B, t)
    _retire(pool, t)
    assert cache.match_and_acquire(A) is None   # evicted post-release
    got = cache.match_and_acquire(B)
    assert got is not None
    cache.release(got)
    assert cache.blocks == 1
    assert pool.used_blocks == 1         # every evicted ref came back


def test_eviction_never_orphans_a_chain_middle(tiny):
    """Evicting a middle block would break its descendants' match path:
    with the deep chain's tail pinned, budget pressure may only evict
    OTHER unpinned leaves, never the chain's interior."""
    cfg, _ = tiny
    pool, cache = _mk_cache(cfg, budget=3)
    chain = list(range(1, 13))           # 3 blocks: parent->child->leaf
    t = _slot_table(pool, 3)
    cache.offer(chain, t)                # exactly fills budget 3
    _retire(pool, t)
    lease = cache.match_and_acquire(chain + [99])  # pin all 3
    assert lease is not None and lease.tokens == 12
    t = _slot_table(pool, 2)
    cache.offer([50] * 6, t)             # unpinned single block: evicted
    _retire(pool, t)
    assert cache.match_and_acquire([50] * 6) is None
    # the pinned chain is intact end to end
    again = cache.match_and_acquire(chain + [99])
    assert again is not None and again.tokens == 12
    cache.release(again)
    cache.release(lease)


def test_forced_eviction_under_pool_pressure(tiny):
    """evict_blocks(): the engine squeezes the trie when the POOL (not
    the trie budget) is scarce — unpinned blocks go even though the trie
    is within budget, pinned ones never do."""
    cfg, _ = tiny
    pool, cache = _mk_cache(cfg, budget=8)
    A, B = [1, 2, 3, 4, 5], [6, 7, 8, 9, 10]
    for toks in (A, B):
        t = _slot_table(pool, 2)
        cache.offer(toks, t)
        _retire(pool, t)
    lease = cache.match_and_acquire(A)   # pin A
    freed = cache.evict_blocks(2)
    assert freed == 1                    # only B was evictable
    assert cache.match_and_acquire(B) is None
    again = cache.match_and_acquire(A + [0])
    assert again is not None             # pinned A survived the squeeze
    cache.release(again)
    cache.release(lease)


# ---------------------------------------------------------------------------
# Engine integration: bitwise one-shot equivalence on the hit path
# ---------------------------------------------------------------------------


def _engine(cfg, params, **overrides):
    kw = dict(max_batch_size=2, max_seq_len=64, max_queue_size=8,
              prefill_bucket=4, prefix_cache_blocks=32)
    kw.update(overrides)
    return ServingEngine(cfg, params, EngineConfig(**kw))


def _reference(cfg, params, prompt, max_new):
    total = len(prompt) + max_new
    toks = np.zeros((1, total), np.int32)
    toks[0, :len(prompt)] = prompt
    out = generate_tokens(cfg, params, jnp.asarray(toks),
                          jnp.asarray([len(prompt)], jnp.int32),
                          eos_id=-1, use_eos_stop=False)
    return np.asarray(out.tokens)[0].tolist()


def _run_seq(engine, specs):
    """Run requests one at a time (each retires — and donates its prefix —
    before the next admission) and return their token lists."""
    try:
        return [engine.submit(p, max_new_tokens=n,
                              use_eos_stop=False).result(timeout=600).tokens
                for p, n in specs]
    finally:
        engine.shutdown()


@pytest.mark.parametrize("fixture", ["tiny", "tiny_int8"])
def test_prefix_hit_bitwise_equals_cold(fixture, request):
    """The acceptance bar: a request admitted via a prefix HIT (cached
    blocks spliced + suffix-only prefill) must produce exactly the
    one-shot greedy trajectory — fp32 and fully-int8 caches."""
    cfg, params = request.getfixturevalue(fixture)
    rng = np.random.default_rng(11)
    prompt = rng.integers(1, cfg.vocab_size, 11).tolist()
    fork = prompt[:8] + rng.integers(1, cfg.vocab_size, 5).tolist()
    engine = _engine(cfg, params).start()
    got = _run_seq(engine, [(prompt, 8),   # cold: populates the cache
                            (prompt, 8),   # full-prefix hit (8 of 11)
                            (fork, 8)])    # shared-prefix hit, new tail
    assert got[0] == _reference(cfg, params, prompt, 8)
    assert got[1] == got[0]                # bitwise: hit == cold
    assert got[2] == _reference(cfg, params, fork, 8)
    snap = engine.metrics.snapshot()
    assert snap["prefix_hits"] == 2 and snap["prefix_misses"] == 1
    # both hits matched the 8-token (2-block) shared prefix
    assert snap["prefix_hit_tokens"]["mean"] == 8.0
    assert snap["prefix_blocks"] > 0


def test_pure_hit_admission_performs_zero_copies(tiny):
    """The zero-copy acceptance bar: shared-prefix admissions are ref
    bumps into the slot table — ``cow_copies_total`` stays 0 across a
    whole hit-heavy sequence (decode appends land in fresh, unshared
    boundary blocks), while the pool gauges show real occupancy."""
    cfg, params = tiny
    rng = np.random.default_rng(16)
    prompt = rng.integers(1, cfg.vocab_size, 13).tolist()
    engine = _engine(cfg, params).start()
    got = _run_seq(engine, [(prompt, 6)] * 3)
    ref = _reference(cfg, params, prompt, 6)
    assert got == [ref] * 3
    snap = engine.metrics.snapshot()
    assert snap["prefix_hits"] == 2
    assert snap["cow_copies_total"] == 0
    assert snap["blocks_used"] > 0          # trie still holds the prefix
    assert 0.0 < snap["kv_cache_util"] <= 1.0


def test_prefix_hit_bitwise_chunked(tiny):
    """Chunked admission: a hit pre-advances the chunk cursor past the
    cached blocks, so only suffix chunks run — same bitwise bar, and the
    prefill_chunks counter proves the skip actually happened."""
    cfg, params = tiny
    rng = np.random.default_rng(12)
    prompt = rng.integers(1, cfg.vocab_size, 11).tolist()
    engine = _engine(cfg, params, prefill_chunk=4).start()
    got = _run_seq(engine, [(prompt, 8), (prompt, 8)])
    ref = _reference(cfg, params, prompt, 8)
    assert got[0] == ref and got[1] == ref
    snap = engine.metrics.snapshot()
    assert snap["prefix_hits"] == 1
    # cold: ceil(11/4)=3 chunks; hit: (12 padded - 8 cached)/4 = 1 chunk
    assert snap["prefill_chunks"] == 4


def test_prefix_cache_disabled(tiny):
    cfg, params = tiny
    rng = np.random.default_rng(13)
    prompt = rng.integers(1, cfg.vocab_size, 11).tolist()
    engine = _engine(cfg, params, prefix_cache_blocks=0).start()
    got = _run_seq(engine, [(prompt, 6), (prompt, 6)])
    assert engine.prefix_cache is None
    ref = _reference(cfg, params, prompt, 6)
    assert got == [ref, ref]
    snap = engine.metrics.snapshot()
    assert snap["prefix_hits"] == 0 and snap["prefix_misses"] == 0


def test_logprob_requests_bypass_the_cache(tiny):
    """Prompt logprobs need every prompt logit in one pass: those
    requests must take the cold whole-prompt prefill (and not count as
    cache lookups), while still returning correct logprobs."""
    cfg, params = tiny
    rng = np.random.default_rng(14)
    prompt = rng.integers(1, cfg.vocab_size, 9).tolist()
    engine = _engine(cfg, params).start()
    try:
        a = engine.submit(prompt, max_new_tokens=4, use_eos_stop=False,
                          return_logprobs=True).result(timeout=600)
        b = engine.submit(prompt, max_new_tokens=4, use_eos_stop=False,
                          return_logprobs=True).result(timeout=600)
    finally:
        engine.shutdown()
    assert a.tokens == b.tokens
    np.testing.assert_allclose(a.logprobs, b.logprobs, rtol=0, atol=0)
    snap = engine.metrics.snapshot()
    assert snap["prefix_hits"] == 0 and snap["prefix_misses"] == 0


def test_pinned_blocks_survive_a_concurrent_eviction_storm(tiny):
    """Ref-count pinning at engine level: while request A decodes (its
    lease live), a wave of distinct-prefix requests overflows a tiny
    budget — A's own retirement offer and every hit must stay coherent,
    and a repeat of A's prompt afterwards still matches bitwise."""
    cfg, params = tiny
    rng = np.random.default_rng(15)
    shared = rng.integers(1, cfg.vocab_size, 9).tolist()
    engine = _engine(cfg, params, prefix_cache_blocks=2,
                     max_batch_size=2).start()
    try:
        first = engine.submit(shared, max_new_tokens=12,
                              use_eos_stop=False)
        storm = [engine.submit(
            rng.integers(1, cfg.vocab_size, 9).tolist(),
            max_new_tokens=2, use_eos_stop=False) for _ in range(6)]
        for h in storm:
            h.result(timeout=600)
        a = first.result(timeout=600)
        b = engine.submit(shared, max_new_tokens=12,
                          use_eos_stop=False).result(timeout=600)
    finally:
        engine.shutdown()
    ref = _reference(cfg, params, shared, 12)
    assert a.tokens == ref and b.tokens == ref
    snap = engine.metrics.snapshot()
    assert snap["prefix_evicted_blocks"] > 0
    # the soft budget recovers once leases drain
    assert engine.prefix_cache.blocks <= 2 + 2  # slack: last offers
