"""Tiered KV: host-RAM block offload, decode preemption, prefix spill.

The acceptance bar for the tier (docs/serving.md, 'Tiered KV'):

* block contents round-trip the host arena **bitwise** — fp32 and int8
  ``{q, scale}`` pools alike — through the same fixed-arity export /
  import executables shipping uses (zero new compiled programs);
* a preempted decode resumes **bitwise**: fill arithmetic and the
  per-request RNG fold counter travel with the suspension, so the final
  token stream equals an uninterrupted run's;
* a prefix spilled to host and re-promoted on the next match serves the
  exact tokens a never-evicted hit serves;
* oversubscribed admission storms keep every ledger balanced — device
  pool AND host tier audited by the LedgerSanitizer each iteration;
* chaos faults at ``host-swap-out`` / ``host-swap-in`` lose nothing:
  a failed demote leaves the device copy decoding in place, a failed
  promote leaves the host copy resident for the re-fetch.
"""

import dataclasses
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.analysis.sanitizers import no_recompiles
from megatron_llm_tpu.config import tiny_config
from megatron_llm_tpu.generation import generate_tokens
from megatron_llm_tpu.models import model as model_lib
from megatron_llm_tpu.resilience.chaos import chaos
from megatron_llm_tpu.serving import EngineConfig, ServingEngine
from megatron_llm_tpu.serving.block_pool import BlockPool, HostKVTier
from megatron_llm_tpu.serving.queue import RequestQueue


@pytest.fixture(scope="module")
def tiny():
    cfg = tiny_config(num_layers=2, vocab_size=64,
                      make_vocab_size_divisible_by=8)
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


def _engine(cfg, params, **overrides):
    kw = dict(max_batch_size=4, max_seq_len=64, max_queue_size=16,
              idle_wait_s=0.005, kv_block_size=8)
    kw.update(overrides)
    return ServingEngine(cfg, params, EngineConfig(**kw))


def _reference(cfg, params, prompt, max_new):
    total = len(prompt) + max_new
    toks = np.zeros((1, total), np.int32)
    toks[0, :len(prompt)] = prompt
    out = generate_tokens(cfg, params, jnp.asarray(toks),
                          jnp.asarray([len(prompt)], jnp.int32),
                          eos_id=-1, use_eos_stop=False)
    return np.asarray(out.tokens)[0].tolist()


def _prompt(cfg, n, seed):
    rng = np.random.default_rng(seed)
    return rng.integers(1, cfg.vocab_size, n).tolist()


# ---------------------------------------------------------------------------
# HostKVTier unit: bitwise round trip, ledger, bandwidth bound
# ---------------------------------------------------------------------------


def _patterned_pool(cfg, n_blocks, bk, bids):
    """A pool whose ``bids`` carry per-block recognizable contents."""
    pool = BlockPool(cfg, n_blocks, bk)

    def stamp(leaf):
        a = np.array(leaf)  # writable copy (np.asarray aliases on CPU)
        for bid in bids:
            fill = (np.arange(a[:, bid].size, dtype=np.float64)
                    % 97 + bid).reshape(a[:, bid].shape)
            a[:, bid] = fill.astype(a.dtype)
        return jnp.asarray(a)

    pool.k_pool = jax.tree.map(stamp, pool.k_pool)
    pool.v_pool = jax.tree.map(stamp, pool.v_pool)
    return pool


@pytest.mark.parametrize("quant", ["fp32", "int8"])
def test_host_tier_roundtrip_bitwise(quant):
    """demote -> pump -> promote restores the exact device bytes into
    fresh blocks, for fp32 and int8 ``{q, scale}`` pools alike."""
    cfg = tiny_config(num_layers=2, vocab_size=64,
                      make_vocab_size_divisible_by=8)
    if quant == "int8":
        cfg = dataclasses.replace(cfg, kv_cache_quant="int8")
    pool = _patterned_pool(cfg, 8, 4, bids=[1, 2, 3])
    before_k = jax.tree.map(lambda a: np.asarray(a).copy(), pool.k_pool)
    before_v = jax.tree.map(lambda a: np.asarray(a).copy(), pool.v_pool)
    tier = HostKVTier(pool, n_host_blocks=4, arity=4)

    pool.reserve(3)
    src = [pool.alloc_reserved() for _ in range(3)]
    assert sorted(src) == [1, 2, 3]
    hids = tier.begin_demote(src, owner="req-a")
    assert tier.in_flight == 1 and tier.host_used == 3
    for bid in src:
        pool.decref(bid)  # staged dense leaves own the bytes now
    assert tier.pump() == 1
    assert tier.in_flight == 0
    assert tier.bw_bytes_per_s > 0 and tier.bw_bytes_per_s != float("inf")

    pool.reserve(3)
    dst = [pool.alloc_reserved() for _ in range(3)]
    tier.promote(hids, dst)
    tier.free(hids)
    assert tier.host_used == 0 and tier.owners() == {}

    for before, after in ((before_k, pool.k_pool), (before_v, pool.v_pool)):
        for b, a in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
            for s, d in zip(src, dst):
                np.testing.assert_array_equal(np.asarray(a)[:, d], b[:, s])


def test_host_tier_ledger_and_bandwidth_bound():
    cfg = tiny_config(num_layers=2, vocab_size=64,
                      make_vocab_size_divisible_by=8)
    pool = BlockPool(cfg, 8, 4)
    tier = HostKVTier(pool, n_host_blocks=2, arity=4)
    assert tier.can_store(2) and not tier.can_store(3)
    assert tier.swap_ok()  # empty backlog always ok
    pool.reserve(2)
    bids = [pool.alloc_reserved(), pool.alloc_reserved()]
    hids = tier.begin_demote(bids, owner="r1")
    with pytest.raises(AssertionError):
        tier.free(hids)  # still in flight
    tier.pump()
    with pytest.raises(AssertionError):
        tier.begin_demote(bids, owner="r2")  # tier exhausted
    tier.free(hids)
    with pytest.raises(AssertionError):
        tier.free(hids)  # double free caught
    stats = tier.stats()
    assert stats["swap_out_blocks"] == 2 and stats["host_blocks_free"] == 2


def test_priority_queue_pop_order():
    """Highest class first, FIFO within a class, FIFO when untagged."""

    class R:
        def __init__(self, name, priority=0):
            self.name, self.priority = name, priority

    q = RequestQueue(max_size=8)
    q.put_many([R("a"), R("b", 2), R("c"), R("d", 2), R("e", 1)])
    assert [q.pop().name for _ in range(5)] == ["b", "d", "e", "a", "c"]
    assert q.pop() is None
    q.put_many([R("x"), R("y"), R("z")])  # all one class: plain FIFO
    assert [q.pop().name for _ in range(3)] == ["x", "y", "z"]


# ---------------------------------------------------------------------------
# Engine: bitwise preemption / resume, oversubscription, observability
# ---------------------------------------------------------------------------

# pool sized so the high-priority admission CANNOT reserve without
# suspending the low-priority decode: 6 usable blocks, victim reserves 4
_PREEMPT_KW = dict(max_batch_size=2, kv_pool_blocks=7, host_kv_blocks=8,
                   prefix_cache_blocks=0, sanitize=True)


def _run_preemption(engine, cfg):
    """Low-priority long decode + a high-priority arrival that must
    preempt it.  Returns (low_result, high_result, low_prompt, hi_prompt,
    low_max_new, hi_max_new)."""
    low_prompt, hi_prompt = _prompt(cfg, 17, 5), _prompt(cfg, 9, 6)
    low_new, hi_new = 12, 10
    started = threading.Event()
    h_low = engine.submit(low_prompt, max_new_tokens=low_new,
                          use_eos_stop=False, priority=0,
                          on_token=lambda t: started.set())
    assert started.wait(timeout=600), "low-priority decode never started"
    h_hi = engine.submit(hi_prompt, max_new_tokens=hi_new,
                         use_eos_stop=False, priority=1)
    r_hi = h_hi.result(timeout=600)
    r_low = h_low.result(timeout=600)
    return r_low, r_hi, low_prompt, hi_prompt, low_new, hi_new


@pytest.mark.parametrize("quant", ["fp32", "int8"])
def test_preempt_resume_bitwise(tiny, quant):
    """A suspended-and-resumed decode produces the exact token stream an
    uninterrupted run produces — KV rows round-trip the host arena
    verbatim and the RNG folds on (seed, count), not slot identity."""
    cfg, params = tiny
    if quant == "int8":
        cfg = dataclasses.replace(cfg, kv_cache_quant="int8")
        params = model_lib.init_params(jax.random.key(0), cfg)
    engine = _engine(cfg, params, **_PREEMPT_KW).start()
    try:
        r_low, r_hi, low_p, hi_p, low_n, hi_n = _run_preemption(engine, cfg)
        snap = engine.metrics.snapshot()
        assert snap["preemptions_total"] >= 1, snap
        assert snap["resumes_total"] >= 1, snap
        assert snap["swap_out_blocks_total"] >= 1
        assert snap["swap_in_blocks_total"] >= 1
        engine.drain(timeout=60)
        assert engine.sanitizer_report == []
    finally:
        engine.shutdown()
    assert engine._scheduler_error is None, engine._scheduler_error
    assert r_low.tokens == _reference(cfg, params, low_p, low_n)
    assert r_hi.tokens == _reference(cfg, params, hi_p, hi_n)


def test_preempt_resume_sampled_rng_carried(tiny):
    """Same bar for a SAMPLED low-priority request: the RNG fold counter
    rides through suspension, so the post-resume samples continue the
    stream a never-preempted run draws."""
    cfg, params = tiny
    low_prompt = _prompt(cfg, 17, 7)
    spec = dict(max_new_tokens=12, temperature=0.9, top_k=5, seed=11,
                use_eos_stop=False)
    # baseline: same sampled request, no competition, no preemption
    engine = _engine(cfg, params, **_PREEMPT_KW).start()
    try:
        baseline = engine.submit(low_prompt, **spec).result(timeout=600)
        assert engine.metrics.snapshot()["preemptions_total"] == 0
    finally:
        engine.shutdown()
    engine = _engine(cfg, params, **_PREEMPT_KW).start()
    try:
        started = threading.Event()
        h_low = engine.submit(low_prompt, priority=0,
                              on_token=lambda t: started.set(), **spec)
        assert started.wait(timeout=600)
        h_hi = engine.submit(_prompt(cfg, 9, 8), max_new_tokens=10,
                             use_eos_stop=False, priority=1)
        h_hi.result(timeout=600)
        preempted = h_low.result(timeout=600)
        assert engine.metrics.snapshot()["preemptions_total"] >= 1
        engine.drain(timeout=60)
        assert engine.sanitizer_report == []
    finally:
        engine.shutdown()
    assert engine._scheduler_error is None, engine._scheduler_error
    assert preempted.tokens == baseline.tokens


def test_oversubscribed_storm_ledgers_balanced(tiny):
    """Admission storm at 2x logical oversubscription under
    MEGATRON_SANITIZE semantics (EngineConfig.sanitize): mixed-priority
    traffic whose worst-case reservations exceed HBM by design.  Every
    request completes with its reference tokens, preemptions actually
    fire, and the drain report is clean — host-owned blocks included."""
    cfg, params = tiny
    # every request needs 4 of the 6 usable device blocks, so two can
    # never co-reside: each higher-class arrival MUST preempt the
    # running lower-class decode (18 host blocks hold several victims)
    engine = _engine(cfg, params, max_batch_size=2, kv_pool_blocks=7,
                     host_kv_blocks=18, prefix_cache_blocks=0,
                     sanitize=True).start()
    jobs = []  # (handle, prompt, max_new)
    try:
        for i in range(9):
            prompt = _prompt(cfg, 17, 100 + i)  # 17 + 14 -> 4 blocks
            h = engine.submit(prompt, max_new_tokens=14,
                              use_eos_stop=False, priority=i % 3)
            jobs.append((h, prompt, 14))
            time.sleep(0.01)  # stagger so decodes are live when the
            #                   next class arrives (preemption pressure)
        results = [h.result(timeout=600) for h, _, _ in jobs]
        snap = engine.metrics.snapshot()
        assert snap["preemptions_total"] >= 1, \
            "storm never exercised preemption; resize the pool"
        assert snap["resumes_total"] == snap["preemptions_total"]
        engine.drain(timeout=120)
        assert engine.sanitizer_report == []
        assert engine.host_tier.host_used == 0
        assert engine.host_tier.in_flight == 0
    finally:
        engine.shutdown()
    assert engine._scheduler_error is None, engine._scheduler_error
    for r, (_, prompt, max_new) in zip(results, jobs):
        assert r.finish_reason == "length"
        assert r.tokens == _reference(cfg, params, prompt, max_new)


def test_tiered_zero_recompiles_after_warmup(tiny):
    """The tier adds no compiled programs: after one warmup
    preempt/resume cycle, further cycles run on warm executables."""
    cfg, params = tiny
    engine = _engine(cfg, params, **_PREEMPT_KW).start()
    try:
        _run_preemption(engine, cfg)  # warm: prefill/decode/export/import
        assert engine.metrics.snapshot()["preemptions_total"] >= 1
        with no_recompiles():
            r_low, r_hi, low_p, hi_p, low_n, hi_n = \
                _run_preemption(engine, cfg)
    finally:
        engine.shutdown()
    assert engine._scheduler_error is None, engine._scheduler_error
    assert r_low.tokens == _reference(cfg, params, low_p, low_n)
    assert r_hi.tokens == _reference(cfg, params, hi_p, hi_n)


def test_kv_snapshot_and_metrics_surface(tiny):
    """GET /kv and /metrics report the host tier: arena occupancy,
    per-request swapped-out counts while suspended, swap/preemption
    counters, resume-latency histogram, and the Prometheus gauges."""
    cfg, params = tiny
    engine = _engine(cfg, params, **_PREEMPT_KW).start()
    try:
        low_prompt = _prompt(cfg, 17, 9)
        started = threading.Event()
        h_low = engine.submit(low_prompt, max_new_tokens=30,
                              use_eos_stop=False, priority=0,
                              on_token=lambda t: started.set())
        assert started.wait(timeout=600)
        h_hi = engine.submit(_prompt(cfg, 9, 10), max_new_tokens=10,
                             use_eos_stop=False, priority=1)
        # while the high-priority decode runs, the low one is suspended:
        # the snapshot must name it with its host-resident block count
        seen_suspended = {}
        deadline = time.monotonic() + 600
        while not seen_suspended and time.monotonic() < deadline:
            host = engine.kv_snapshot().get("host_tier") or {}
            seen_suspended = dict(host.get("suspended", {}))
            time.sleep(0.002)
        h_hi.result(timeout=600)
        h_low.result(timeout=600)
        assert seen_suspended, "suspended request never surfaced in /kv"
        info = seen_suspended[h_low.rid]
        assert info["blocks"] >= 1 and info["priority"] == 0

        snap = engine.kv_snapshot()
        host = snap["host_tier"]
        assert host["n_host_blocks"] == 8
        assert host["swap_out_blocks"] >= 1
        assert host["swap_bw_bytes_per_s"] > 0.0

        m = engine.metrics.snapshot()
        assert m["preemptions_total"] >= 1
        assert m["swap_bytes_total"] > 0
        assert m["resume_latency"]["count"] >= 1
        assert m["prefix_promotions_total"] == 0  # no cache configured
        assert "host_blocks_used" in m and "host_blocks_free" in m
        prom_names = {f.name for f in engine.metrics.collect()}
        assert "serving_host_blocks_used" in prom_names
        assert "serving_host_blocks_free" in prom_names
        assert "serving_swap_out_blocks_total" in prom_names
        assert "serving_preemptions_total" in prom_names
        assert "serving_resume_latency_seconds" in prom_names
    finally:
        engine.shutdown()
    assert engine._scheduler_error is None, engine._scheduler_error


# ---------------------------------------------------------------------------
# Prefix-cache spill -> promote
# ---------------------------------------------------------------------------


def test_prefix_spill_promote_hit_equals_never_evicted(tiny):
    """A prefix evicted under budget pressure spills to host and serves
    the NEXT identical prompt via promotion, token-for-token equal to a
    never-evicted hit — the effective prefix cache is RAM-sized."""
    cfg, params = tiny
    prompt_a = _prompt(cfg, 17, 21)  # 2 cached blocks at bk=8
    prompt_b = _prompt(cfg, 17, 22)
    max_new = 6
    kw = dict(max_batch_size=2, prefix_cache_blocks=2, host_kv_blocks=8,
              sanitize=True)

    # never-evicted baseline: A twice back to back, second is a pure hit
    engine = _engine(cfg, params, **kw).start()
    try:
        engine.submit(prompt_a, max_new_tokens=max_new,
                      use_eos_stop=False).result(timeout=600)
        never_evicted = engine.submit(prompt_a, max_new_tokens=max_new,
                                      use_eos_stop=False).result(timeout=600)
        assert engine.metrics.snapshot()["prefix_hits"] >= 1
    finally:
        engine.shutdown()

    engine = _engine(cfg, params, **kw).start()
    try:
        engine.submit(prompt_a, max_new_tokens=max_new,
                      use_eos_stop=False).result(timeout=600)
        # B's retirement offer overflows the 2-block budget: A's blocks
        # spill to the host tier instead of dropping
        engine.submit(prompt_b, max_new_tokens=max_new,
                      use_eos_stop=False).result(timeout=600)
        deadline = time.monotonic() + 600
        while (engine.prefix_cache.host_blocks < 1
               and time.monotonic() < deadline):
            time.sleep(0.002)
        assert engine.prefix_cache.host_blocks >= 1, "eviction never spilled"
        spilled_hit = engine.submit(prompt_a, max_new_tokens=max_new,
                                    use_eos_stop=False).result(timeout=600)
        snap = engine.metrics.snapshot()
        assert snap["prefix_promotions_total"] >= 1, snap
        assert snap["prefix_hits"] >= 1
        engine.drain(timeout=60)
        assert engine.sanitizer_report == []
    finally:
        engine.shutdown()
    assert engine._scheduler_error is None, engine._scheduler_error
    assert spilled_hit.tokens == never_evicted.tokens
    assert spilled_hit.tokens == _reference(cfg, params, prompt_a, max_new)


# ---------------------------------------------------------------------------
# Chaos: swap faults lose nothing
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_chaos_swap_out_fault_keeps_device_copy(tiny):
    """host-swap-out armed: the demote fails BEFORE any state mutates,
    so the victim keeps decoding on device (no preemption) and both
    requests still finish with their reference tokens, ledgers clean."""
    cfg, params = tiny
    engine = _engine(cfg, params, **_PREEMPT_KW).start()
    try:
        chaos().fail_io("host-swap-out", times=100)
        r_low, r_hi, low_p, hi_p, low_n, hi_n = _run_preemption(engine, cfg)
        snap = engine.metrics.snapshot()
        assert snap["preemptions_total"] == 0, \
            "demote fault must abort the preemption"
        assert engine.host_tier.host_used == 0
        engine.drain(timeout=120)
        assert engine.sanitizer_report == []
    finally:
        chaos().reset()
        engine.shutdown()
    assert engine._scheduler_error is None, engine._scheduler_error
    assert r_low.tokens == _reference(cfg, params, low_p, low_n)
    assert r_hi.tokens == _reference(cfg, params, hi_p, hi_n)


@pytest.mark.chaos
def test_chaos_swap_in_fault_refetches(tiny):
    """host-swap-in armed for exactly one attempt: the first resume
    faults with the host copy intact, a later scheduler iteration
    re-fetches, and the resumed trajectory is still bitwise."""
    cfg, params = tiny
    engine = _engine(cfg, params, **_PREEMPT_KW).start()
    try:
        chaos().fail_io("host-swap-in", times=1)
        r_low, r_hi, low_p, hi_p, low_n, hi_n = _run_preemption(engine, cfg)
        snap = engine.metrics.snapshot()
        assert snap["preemptions_total"] >= 1
        assert snap["resumes_total"] >= 1
        engine.drain(timeout=120)
        assert engine.sanitizer_report == []
        assert engine.host_tier.host_used == 0
    finally:
        chaos().reset()
        engine.shutdown()
    assert engine._scheduler_error is None, engine._scheduler_error
    assert r_low.tokens == _reference(cfg, params, low_p, low_n)
    assert r_hi.tokens == _reference(cfg, params, hi_p, hi_n)


@pytest.mark.chaos
def test_chaos_prefix_spill_fault_drops_cleanly(tiny):
    """host-swap-out armed during prefix eviction: _spill fails before
    mutating, the victim falls back to a plain drop, and the next
    identical prompt simply re-prefills — correct, just cold."""
    cfg, params = tiny
    prompt_a, prompt_b = _prompt(cfg, 17, 31), _prompt(cfg, 17, 32)
    engine = _engine(cfg, params, max_batch_size=2, prefix_cache_blocks=2,
                     host_kv_blocks=8, sanitize=True).start()
    try:
        engine.submit(prompt_a, max_new_tokens=6,
                      use_eos_stop=False).result(timeout=600)
        chaos().fail_io("host-swap-out", times=100)
        engine.submit(prompt_b, max_new_tokens=6,
                      use_eos_stop=False).result(timeout=600)
        # B's offer overflowed the budget while the swap site faulted:
        # A's blocks were plain-dropped, nothing landed on the host
        assert engine.prefix_cache.host_blocks == 0
        chaos().reset()
        # A is gone from the cache entirely — this is a cold re-prefill,
        # not a promotion (its own retirement may spill B; that's fine)
        r = engine.submit(prompt_a, max_new_tokens=6,
                          use_eos_stop=False).result(timeout=600)
        assert engine.metrics.snapshot()["prefix_promotions_total"] == 0
        engine.drain(timeout=60)
        assert engine.sanitizer_report == []
    finally:
        chaos().reset()
        engine.shutdown()
    assert engine._scheduler_error is None, engine._scheduler_error
    assert r.tokens == _reference(cfg, params, prompt_a, 6)
