"""Multi-tenant LoRA across the cluster: per-replica registry clones,
post-build adapter registration, adapter-affinity routing, and the
rolling weight swap (Router.rolling_swap) as the zero-downtime deploy
plane.

Runs on the 8-virtual-device CPU mesh from conftest; replicas are
tp=1 engines on disjoint single-device slices, so the per-engine
bitwise guarantees of test_adapters.py carry over replica-for-replica.
"""

import dataclasses
import time

import jax
import numpy as np
import pytest

from megatron_llm_tpu.config import tiny_config
from megatron_llm_tpu.models import model as model_lib
from megatron_llm_tpu.ops.lora import init_lora_adapter
from megatron_llm_tpu.serving import (
    AdapterRegistry,
    EngineConfig,
    build_cluster,
)

PROMPT = [3, 5, 7, 11, 13]


@pytest.fixture(scope="module")
def tiny():
    cfg = tiny_config(num_layers=2, vocab_size=64,
                      make_vocab_size_divisible_by=8)
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


def _adapter(cfg, seed, rank=4):
    ad = init_lora_adapter(cfg, jax.random.key(seed), rank, alpha=32.0)
    return dataclasses.replace(ad, factors={
        t: {"a": f["a"],
            "b": jax.random.normal(jax.random.key(seed + 500),
                                   f["b"].shape, f["b"].dtype) * 0.05}
        for t, f in ad.factors.items()})


def _cluster(cfg, params, replicas=2, **ecfg):
    kw = dict(max_batch_size=2, max_seq_len=96, max_queue_size=32,
              adapter_cache_slots=2, prefix_cache_blocks=0)
    kw.update(ecfg)
    reg = AdapterRegistry(cfg, n_slots=2, rank=4)
    reg.register("tenant-a", _adapter(cfg, 11))
    return build_cluster(cfg, params, EngineConfig(**kw),
                         replicas=replicas, adapters=reg).start()


def test_routed_adapters_match_alone_and_affinity(tiny):
    """Adapter requests through the router — including one registered
    AFTER the cluster was built, via Router.register_adapter — return
    the same tokens as an alone run, whichever replica serves them
    (every replica holds the same store via registry clones)."""
    cfg, params = tiny
    router = _cluster(cfg, params)
    try:
        router.register_adapter("tenant-b", _adapter(cfg, 22))

        def alone(aid):
            kw = {} if aid is None else {"adapter_id": aid}
            return router.submit(PROMPT, 8, seed=1, use_eos_stop=False,
                                 **kw).result(600).tokens

        ref = {aid: alone(aid) for aid in ("tenant-a", "tenant-b", None)}
        assert ref["tenant-a"] != ref[None] != ref["tenant-b"]
        handles = [router.submit(PROMPT, 8, seed=1, use_eos_stop=False,
                                 **({} if aid is None
                                    else {"adapter_id": aid}))
                   for aid in ("tenant-a", None, "tenant-b", "tenant-a")]
        out = [h.result(600).tokens for h in handles]
        assert out == [ref["tenant-a"], ref[None], ref["tenant-b"],
                       ref["tenant-a"]]
        # affinity: the served adapters are resident somewhere, and a
        # replica that has tenant-a resident wins the tiebreak for it
        assert any(r.engine.adapters.is_resident("tenant-a")
                   for r in router.replicas)
    finally:
        router.shutdown()
    for r in router.replicas:
        assert r.engine.sanitizer_report == []


def test_register_adapter_needs_a_registry(tiny):
    cfg, params = tiny
    router = build_cluster(cfg, params, EngineConfig(
        max_batch_size=2, max_seq_len=64), replicas=2).start()
    try:
        with pytest.raises(ValueError, match="registry|adapter"):
            router.register_adapter("t", _adapter(cfg, 1))
    finally:
        router.shutdown()


def test_rolling_swap_mid_traffic_loses_nothing(tiny):
    """rolling_swap through a 2-replica cluster mid-traffic: every
    in-flight stream completes with all its tokens exactly once
    (draining replicas migrate live decodes to siblings), both replicas
    end up on the new tree, and the ledgers balance."""
    cfg, params = tiny
    router = _cluster(cfg, params)
    params2 = model_lib.init_params(jax.random.key(99), cfg)
    got = {}
    try:
        handles = []
        for i in range(4):
            got[i] = []
            handles.append(router.submit(
                PROMPT, 48, seed=2 + i, use_eos_stop=False,
                adapter_id="tenant-a" if i % 2 else None,
                on_token=got[i].append))
        time.sleep(0.05)
        report = router.rolling_swap(params2)
        results = [h.result(600) for h in handles]
    finally:
        router.shutdown()
    for i, r in enumerate(results):
        gen = r.tokens[len(PROMPT):]
        assert len(gen) == 48, f"request {i} lost tokens"
        assert got[i] == gen, f"request {i} stream != result"
    assert len(report["replicas"]) == 2
    snap = router.snapshot()
    assert snap["router"]["rolling_swaps_total"] == 1
    for r in router.replicas:
        assert r.engine.metrics.snapshot()["param_swaps"] == 1
        assert not r.draining
        assert r.engine.sanitizer_report == []


def test_migrated_adapter_request_stays_bitwise(tiny):
    """Live-migrating an adapter-decorated decode mid-stream: the
    shipment carries only the adapter_id, the destination re-pins it
    out of its own registry clone, and the finished stream is bitwise
    equal to an unmigrated run."""
    cfg, params = tiny
    router = _cluster(cfg, params)
    try:
        ref = router.submit(PROMPT, 32, seed=5, use_eos_stop=False,
                            adapter_id="tenant-a").result(600).tokens
        h = router.submit(PROMPT, 32, seed=5, use_eos_stop=False,
                          adapter_id="tenant-a")
        time.sleep(0.05)
        moved = router.migrate_request(h)
        r = h.result(600)
        snap = router.snapshot()
    finally:
        router.shutdown()
    assert r.tokens == ref
    if moved:     # finished-before-migration is a legal race; when the
        # shipment really happened, the adopting replica re-pinned
        assert snap["router"]["migrations_total"] >= 1
    for rep in router.replicas:
        assert rep.engine.sanitizer_report == []


def test_rolling_swap_single_replica_rides_the_fence(tiny):
    """With no sibling to migrate to, the lone replica swaps in place:
    nothing is failed or requeued, the stream just crosses the fence."""
    cfg, params = tiny
    router = _cluster(cfg, params, replicas=1)
    params2 = model_lib.init_params(jax.random.key(7), cfg)
    try:
        h = router.submit(PROMPT, 32, use_eos_stop=False,
                          adapter_id="tenant-a")
        time.sleep(0.05)
        report = router.rolling_swap(params2)
        r = h.result(600)
    finally:
        router.shutdown()
    assert len(r.tokens) == len(PROMPT) + 32
    assert report["migrated"] == 0 and report["requeued"] == 0


def test_rolling_swap_rejects_mismatched_tree(tiny):
    """A bad tree raises out of rolling_swap with the replica undrained
    — the cluster keeps serving on the old weights."""
    cfg, params = tiny
    router = _cluster(cfg, params)
    bad_cfg = tiny_config(num_layers=1, vocab_size=64,
                          make_vocab_size_divisible_by=8)
    try:
        with pytest.raises(ValueError, match="structure|shape"):
            router.rolling_swap(model_lib.init_params(jax.random.key(1),
                                                      bad_cfg))
        assert all(not r.draining for r in router.replicas)
        r = router.submit(PROMPT, 6, use_eos_stop=False).result(600)
        assert len(r.tokens) == len(PROMPT) + 6
    finally:
        router.shutdown()
