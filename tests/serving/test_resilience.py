"""Serving-side robustness: per-request deadlines and graceful drain.

A request past its wall-clock deadline must stop occupying capacity —
whether it is still queued or mid-decode — and finish with reason
"timeout".  A draining engine must finish what it accepted and reject
what it didn't, so a SIGTERM'd server never drops in-flight responses.
"""

import threading
import time

import jax
import numpy as np
import pytest

from megatron_llm_tpu.config import tiny_config
from megatron_llm_tpu.models import model as model_lib
from megatron_llm_tpu.serving import EngineConfig, QueueFull, ServingEngine

pytestmark = pytest.mark.chaos


@pytest.fixture(scope="module")
def tiny():
    cfg = tiny_config(num_layers=2, vocab_size=64,
                      make_vocab_size_divisible_by=8)
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


def _engine(cfg, params, **overrides):
    kw = dict(max_batch_size=4, max_seq_len=64, max_queue_size=16,
              idle_wait_s=0.005)
    kw.update(overrides)
    return ServingEngine(cfg, params, EngineConfig(**kw))


def test_queued_request_expires_under_pressure(tiny):
    """A request that spends its whole deadline waiting in the queue is
    expired by the scheduler without ever taking a slot."""
    cfg, params = tiny
    engine = _engine(cfg, params)
    engine.start()
    engine.pause()  # deterministic queue pressure: nothing admits
    try:
        h = engine.submit([5, 9, 3], max_new_tokens=4, deadline_s=0.05)
        r = h.result(timeout=60)
        assert r.finish_reason == "timeout"
        assert r.tokens == [5, 9, 3]  # nothing generated
        snap = engine.metrics.snapshot()
        assert snap["timeouts"] == 1
        assert snap["admitted"] == 0
        assert len(engine.queue) == 0
    finally:
        engine.shutdown()


def test_active_request_expires_mid_generation(tiny):
    """A slow in-flight generation is retired at its deadline with the
    tokens produced so far."""
    cfg, params = tiny
    engine = _engine(cfg, params, max_seq_len=128)
    engine.start()
    try:
        # warm the compile caches so the deadline clock measures decode
        # time, not XLA compile time
        engine.submit([1, 2, 3], max_new_tokens=2,
                      use_eos_stop=False).result(timeout=600)
        # pace the decode from the token callback so a 0.3s deadline
        # reliably lands in the middle of the 100-token budget
        h = engine.submit([1, 2, 3], max_new_tokens=100, deadline_s=0.3,
                          use_eos_stop=False,
                          on_token=lambda t: time.sleep(0.02))
        r = h.result(timeout=600)
        assert r.finish_reason == "timeout"
        generated = len(r.tokens) - r.prompt_len
        assert 0 < generated < 100  # partial progress, then expiry
        assert engine.metrics.snapshot()["timeouts"] == 1
    finally:
        engine.shutdown()


def test_default_deadline_from_engine_config(tiny):
    cfg, params = tiny
    engine = _engine(cfg, params, default_deadline_s=0.05)
    engine.start()
    engine.pause()
    try:
        # no per-request deadline: the config default applies
        h = engine.submit([5, 9, 3], max_new_tokens=4)
        assert h.result(timeout=60).finish_reason == "timeout"
        # an explicit per-request deadline overrides the default
        h2 = engine.submit([5, 9, 3], max_new_tokens=4, deadline_s=3600)
        time.sleep(0.2)
        assert not h2.done()
        h2.cancel()
    finally:
        engine.shutdown()


def test_drain_completes_in_flight_then_rejects(tiny):
    cfg, params = tiny
    engine = _engine(cfg, params)
    engine.start()
    try:
        handles = [engine.submit([i + 1, 2, 3], max_new_tokens=6,
                                 use_eos_stop=False) for i in range(6)]
        assert engine.drain(timeout=600) is True
        # everything accepted before the drain completed normally
        for h in handles:
            assert h.result(timeout=1).finish_reason == "length"
        # post-drain submissions are backpressure-rejected
        with pytest.raises(QueueFull):
            engine.submit([7, 8, 9], max_new_tokens=2)
        assert engine.metrics.snapshot()["rejected_draining"] == 1
    finally:
        engine.shutdown()


def test_drain_never_started_engine(tiny):
    cfg, params = tiny
    engine = _engine(cfg, params)
    assert engine.drain(timeout=1) is True


def test_drain_timeout_returns_false(tiny):
    cfg, params = tiny
    engine = _engine(cfg, params)
    engine.start()
    engine.pause()  # requests can never finish
    try:
        engine.submit([5, 9, 3], max_new_tokens=4)
        assert engine.drain(timeout=0.1) is False
    finally:
        engine.shutdown()


def test_server_graceful_shutdown_drains(tiny):
    """Server-level contract: graceful_shutdown() lets the in-flight
    request finish (not 'error', not dropped) before the listener dies."""
    from megatron_llm_tpu.generation.server import MegatronServer
    from megatron_llm_tpu.tokenizer.tokenizer import NullTokenizer

    cfg, params = tiny
    server = MegatronServer(cfg, params,
                            NullTokenizer(vocab_size=cfg.vocab_size),
                            max_batch_size=2, engine_max_seq_len=64)
    server.run(host="127.0.0.1", port=0, block=False,
               graceful_sigterm=False)
    try:
        results = {}

        def client():
            results["resp"] = server.service.handle(
                {"prompts": ["5 9 3"], "tokens_to_generate": 4})

        t = threading.Thread(target=client)
        t.start()
        time.sleep(0.05)  # let the request reach the engine
        assert server.graceful_shutdown(drain_timeout_s=600) is True
        t.join(timeout=600)
        status, payload = results["resp"]
        assert status == 200
        assert payload["text"]
        # drained service rejects new work with backpressure, not a crash
        status2, _ = server.service.handle(
            {"prompts": ["1 2 3"], "tokens_to_generate": 2})
        assert status2 == 503
    finally:
        server.shutdown()


def test_service_request_deadline_plumbs_to_engine(tiny):
    from megatron_llm_tpu.generation.server import GenerationService
    from megatron_llm_tpu.tokenizer.tokenizer import NullTokenizer

    cfg, params = tiny
    svc = GenerationService(cfg, params,
                            NullTokenizer(vocab_size=cfg.vocab_size),
                            max_batch_size=2, engine_max_seq_len=64,
                            request_deadline_s=12.5)
    try:
        assert svc.engine.config.default_deadline_s == 12.5
    finally:
        svc.close()
