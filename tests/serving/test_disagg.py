"""Disaggregated prefill/decode tests (CPU, 8 virtual devices, tiny model).

Four contracts, each load-bearing for the KV-block shipping primitive
(serving/block_pool.py) and the disaggregated cluster (serving/cluster/):

- **export/import round trip** — a block-table-ordered slice of one pool
  moves into another pool bitwise, fp32 and int8 ``{q, scale}`` leaves
  verbatim (never dequantized), through shuffled non-contiguous tables,
  with the shipment ref-count handoff keeping the ledger balanced.
- **disagg parity** — a 1 prefill + 1 decode cluster must produce
  bitwise-identical tokens to the single mixed engine across fp32/int8-kv
  × pipelined/classic × speculation on/off (plus the int4 weight-policy
  route), every request actually shipped, with zero post-warmup
  recompiles on *both* engines.
- **live migration** — moving an actively decoding request between
  replicas mid-stream loses no accepted token: the client stream and the
  final trajectory are bitwise-equal to an unmigrated run, and the
  ledger sanitizer stays balanced on both replicas.
- **sanitizer coverage** — a chaos-injected block leak during the
  migration handoff is caught by the LedgerSanitizer and attributed to
  the request that owned the block; unreconciled shipment ledgers
  (missing ``end_ship``) trip the boundedness check.
"""

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.analysis.sanitizers import LedgerError, no_recompiles
from megatron_llm_tpu.config import tiny_config
from megatron_llm_tpu.models import model as model_lib
from megatron_llm_tpu.obs.logging import EVENT_LOG
from megatron_llm_tpu.resilience.chaos import chaos
from megatron_llm_tpu.serving import (
    EngineConfig,
    ServingEngine,
    build_cluster,
    build_disagg_cluster,
)
from megatron_llm_tpu.serving.block_pool import BlockPool


@pytest.fixture(scope="module")
def tiny():
    cfg = tiny_config(num_layers=2, vocab_size=64,
                      make_vocab_size_divisible_by=8)
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


def _prompts(cfg, n, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, cfg.vocab_size,
                         int(rng.integers(4, 12))).tolist()
            for _ in range(n)]


def _run(engine_or_router, specs, timeout=120):
    handles = engine_or_router.submit_many(specs)
    return [h.result(timeout) for h in handles]


def _reference_tokens(cfg, params, specs, **cfg_overrides):
    """Uninterrupted single mixed-role engine run — the parity baseline."""
    kw = dict(max_batch_size=2, max_seq_len=64, max_queue_size=32)
    kw.update(cfg_overrides)
    engine = ServingEngine(cfg, params, EngineConfig(**kw)).start()
    try:
        return [list(r.tokens) for r in _run(engine, specs)]
    finally:
        engine.shutdown()


# ---------------------------------------------------------------------------
# pool primitive: export/import round trip, bitwise, ledger handoff
# ---------------------------------------------------------------------------

def _patterned(pool):
    """Write a distinct deterministic pattern into every leaf element so a
    block landing one row off — or through a dequantize round trip —
    cannot compare equal."""
    def pat(i, a):
        vals = (jnp.arange(a.size) * 7 + i * 131) % 251
        return vals.reshape(a.shape).astype(a.dtype)
    pool.k_pool = jax.tree.map(
        lambda a, _i=iter(range(100)): pat(next(_i), a), pool.k_pool)
    pool.v_pool = jax.tree.map(
        lambda a, _i=iter(range(100, 200)): pat(next(_i), a), pool.v_pool)


@pytest.mark.parametrize("kv_quant", ["none", "int8"])
def test_export_import_roundtrip_bitwise(kv_quant):
    """Shuffled, non-contiguous source blocks land at different (also
    shuffled) destination blocks with every leaf element identical.  The
    int8 pool's {q, scale} leaves must arrive in their original dtypes —
    quantized KV ships quantized, never through a dequantize round trip.
    (KV pools only come in fp32/int8 — int4 is a weight-only policy; its
    disagg coverage is the cluster parity test below.)"""
    cfg = tiny_config(num_layers=2, vocab_size=64,
                      make_vocab_size_divisible_by=8)
    if kv_quant != "none":
        cfg = dataclasses.replace(cfg, kv_cache_quant=kv_quant).validate()
    src = BlockPool(cfg, 12, 4)
    dst = BlockPool(cfg, 12, 4)
    _patterned(src)

    src_bids = [7, 3, 9, 5]                  # shuffled, non-contiguous
    dst_bids = [2, 10, 1, 6]
    arity = 6                                # > len(bids): trash-padded
    k_d, v_d = src.export_blocks(src_bids, arity)
    # dense leaves keep the pool's own dtypes end to end
    for d_leaf, p_leaf in zip(jax.tree.leaves(k_d),
                              jax.tree.leaves(src.k_pool)):
        assert d_leaf.dtype == p_leaf.dtype
    if kv_quant == "int8":
        assert any(leaf.dtype == jnp.int8 for leaf in jax.tree.leaves(k_d))

    scatter = np.full(arity, BlockPool.TRASH, np.int32)
    scatter[:len(dst_bids)] = dst_bids
    dst.import_blocks(k_d, v_d, scatter)

    for s_bid, d_bid in zip(src_bids, dst_bids):
        for s_leaf, d_leaf in zip(jax.tree.leaves(src.k_pool),
                                  jax.tree.leaves(dst.k_pool)):
            np.testing.assert_array_equal(np.asarray(s_leaf[:, s_bid]),
                                          np.asarray(d_leaf[:, d_bid]))
        for s_leaf, d_leaf in zip(jax.tree.leaves(src.v_pool),
                                  jax.tree.leaves(dst.v_pool)):
            np.testing.assert_array_equal(np.asarray(s_leaf[:, s_bid]),
                                          np.asarray(d_leaf[:, d_bid]))


def test_ship_ledger_handoff_is_atomic():
    """begin_ship takes the shipment's refs BEFORE the source slot drops
    its own, so counts never touch zero mid-transfer; end_ship reconciles
    and frees.  stats() surfaces the in-flight count throughout."""
    cfg = tiny_config(num_layers=2, vocab_size=64,
                      make_vocab_size_divisible_by=8)
    pool = BlockPool(cfg, 8, 4)
    assert pool.reserve(3)
    bids = [pool.alloc_reserved() for _ in range(3)]

    pool.begin_ship("ship-t", "req-t", bids, nbytes=123)
    assert pool.stats()["shipments_in_flight"] == 1
    assert all(pool.ref(b) == 2 for b in bids)
    for b in bids:                       # the "slot release" half
        pool.decref(b)
    # mid-transfer: blocks alive, owned solely by the shipment
    assert all(pool.ref(b) == 1 for b in bids)
    assert pool.used_blocks == 3
    pool.end_ship("ship-t")
    assert pool.stats()["shipments_in_flight"] == 0
    assert pool.used_blocks == 0
    assert pool.free_blocks == pool.usable_blocks
    with pytest.raises(KeyError):        # double end_ship is a bug
        pool.end_ship("ship-t")


# ---------------------------------------------------------------------------
# disaggregated cluster: prefill ships to decode, bitwise, zero recompiles
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("spec", [0, 4], ids=["spec_off", "spec_on"])
@pytest.mark.parametrize("pipeline", [True, False],
                         ids=["pipelined", "classic"])
@pytest.mark.parametrize("kv_quant", ["none", "int8"])
def test_disagg_bitwise_matches_colocated(tiny, devices, kv_quant,
                                          pipeline, spec):
    cfg, params = tiny
    if kv_quant != "none":
        cfg = dataclasses.replace(cfg, kv_cache_quant=kv_quant).validate()
    # repetitive tails give the n-gram drafter something to accept when
    # speculation is on; bitwise parity must hold either way
    base = _prompts(cfg, 2, seed=13)
    specs = [dict(prompt=(p + p)[:10], max_new_tokens=10, seed=i,
                  use_eos_stop=False) for i, p in enumerate(base)]
    kw = dict(prefill_bucket=16, pipeline_decode=pipeline,
              spec_draft_len=spec, sanitize=True)
    ref = _reference_tokens(cfg, params, specs, **kw)

    router = build_disagg_cluster(
        cfg, params,
        EngineConfig(max_batch_size=2, max_seq_len=64, max_queue_size=32,
                     **kw),
        prefill_replicas=1, decode_replicas=1).start()
    try:
        assert [r.role for r in router.replicas] == ["prefill", "decode"]
        # warmup compiles every workload shape on BOTH engines: the
        # prefill bucket + export gather on the prefill replica, the
        # import scatter + decode (and verify) steps on the decode one
        _run(router, specs)
        with no_recompiles():
            got = [list(r.tokens) for r in _run(router, specs)]
        snap = router.snapshot()
        assert got == ref
        # every request genuinely shipped — nothing decoded on the
        # prefill replica via the local fallback
        assert snap["router"]["ships_total"] == 2 * len(specs)
        assert snap["shipments_in_flight"] == []
        pre, dec = router.replicas
        assert pre.engine.metrics.counters["ships_out_total"] == \
            2 * len(specs)
        assert dec.engine.metrics.counters["ships_in_total"] == \
            2 * len(specs)
        # phase routing sent every submission to the prefill replica
        assert pre.dispatched >= 2 * len(specs)
    finally:
        router.shutdown()
    # shutdown ran each sanitizer's leak report: the shipment handoffs
    # left both replicas' ledgers balanced
    for rep in router.replicas:
        assert rep.engine.sanitizer_report == []


def test_disagg_int4_weight_policy_bitwise(tiny, devices):
    """Shipping composes with the serving weight-precision policy: an
    int4-policy cluster (int8 KV pool) matches its own single-engine
    baseline bitwise."""
    from megatron_llm_tpu.ops.quant import quantize_params

    cfg, params = tiny
    qcfg = dataclasses.replace(cfg, kv_cache_quant="int8").validate()
    qparams = quantize_params(params, "int4")
    specs = [dict(prompt=p, max_new_tokens=8, seed=i, use_eos_stop=False)
             for i, p in enumerate(_prompts(qcfg, 2, seed=17))]
    ref = _reference_tokens(qcfg, qparams, specs, prefill_bucket=16)
    router = build_disagg_cluster(
        qcfg, qparams,
        EngineConfig(max_batch_size=2, max_seq_len=64, max_queue_size=32,
                     prefill_bucket=16, sanitize=True),
        prefill_replicas=1, decode_replicas=1).start()
    try:
        got = [list(r.tokens) for r in _run(router, specs)]
        assert got == ref
        assert router.snapshot()["router"]["ships_total"] == len(specs)
    finally:
        router.shutdown()
    for rep in router.replicas:
        assert rep.engine.sanitizer_report == []


def test_disagg_observability_surface(tiny, devices):
    """EVENT_LOG ``shipped`` lines carry request id + both replica ids,
    ship spans land on the request's tid track, Prometheus exposition
    carries the cluster ship counters and per-role replica gauges."""
    cfg, params = tiny
    EVENT_LOG.clear()
    specs = [dict(prompt=p, max_new_tokens=6, seed=i, use_eos_stop=False)
             for i, p in enumerate(_prompts(cfg, 2, seed=19))]
    router = build_disagg_cluster(
        cfg, params,
        EngineConfig(max_batch_size=2, max_seq_len=64, max_queue_size=32),
        prefill_replicas=1, decode_replicas=1).start()
    try:
        handles = router.submit_many(specs)
        results = [h.result(120) for h in handles]
        assert all(r.finish_reason == "length" for r in results)
        shipped = EVENT_LOG.recent(event="shipped")
        assert len(shipped) == len(specs)
        for e in shipped:
            assert e["request_id"]
            assert e["from_replica"] == "replica-0"
            assert e["to_replica"] == "replica-1"
            assert e["bytes"] > 0 and e["blocks"] >= 1
        events = router.trace.chrome_trace()["traceEvents"]
        ship_spans = [e for e in events if e["name"] == "ship"]
        assert len(ship_spans) == len(specs)
        # ship spans ride the request's tid track, so a per-request
        # timeline shows the handoff inline with its other spans
        assert {e["tid"] for e in ship_spans} == \
            {h.request_id for h in handles}

        fams = {f.name: f for f in router.metrics.collect()}
        assert fams["cluster_ships_total"].samples[0].value == len(specs)
        assert fams["cluster_migrations_total"].samples[0].value == 0
        assert fams["cluster_ship_bytes_total"].samples[0].value > 0
        assert fams["cluster_shipments_in_flight"].samples[0].value == 0
        roles = {s.labels["role"]: s.value
                 for s in fams["cluster_replicas_by_role"].samples}
        assert roles == {"prefill": 1, "decode": 1}
        snap = router.snapshot()
        assert snap["router"]["roles"] == {"prefill": 1, "decode": 1}
        assert snap["router"]["ship_bytes_total"] > 0
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# live migration: bitwise mid-stream handoff, chaos leak, boundedness
# ---------------------------------------------------------------------------

def _wait_tokens(stream, n, timeout=60):
    deadline = time.perf_counter() + timeout
    while len(stream) < n:
        assert time.perf_counter() < deadline, \
            f"stream produced {len(stream)} tokens, wanted {n}"
        time.sleep(0.01)


def test_migrate_mid_stream_bitwise_zero_loss(tiny):
    cfg, params = tiny
    n = 2
    specs = [dict(prompt=p, max_new_tokens=24, seed=i, use_eos_stop=False)
             for i, p in enumerate(_prompts(cfg, n, seed=23))]
    ref = _reference_tokens(cfg, params, specs)

    EVENT_LOG.clear()
    streams = {i: [] for i in range(n)}
    router = build_cluster(
        cfg, params,
        EngineConfig(max_batch_size=2, max_seq_len=64, max_queue_size=32,
                     sanitize=True),
        replicas=2).start()
    try:
        handles = router.submit_many([
            dict(s, on_token=(lambda i: (lambda t:
                 streams[i].append(int(t))))(i))
            for i, s in enumerate(specs)])
        _wait_tokens(streams[0], 3)
        src = handles[0]._rr.replica
        dst_id = next(r.id for r in router.replicas if r is not src)
        # pause the source so the request cannot finish while we migrate
        # (control ops still run on a paused scheduler by design)
        src.engine.pause()
        try:
            assert router.migrate_request(handles[0], to_replica_id=dst_id)
        finally:
            src.engine.resume()
        assert handles[0]._rr.replica.id == dst_id
        results = [h.result(120) for h in handles]
    finally:
        router.shutdown()
    for rep in router.replicas:
        assert rep.engine.sanitizer_report == []

    got = [list(r.tokens) for r in results]
    assert got == ref
    # zero lost, zero replayed: the stream saw exactly the generated
    # suffix once — migration moves the live request, nothing re-runs
    for i, r in enumerate(results):
        assert streams[i] == list(map(int, r.tokens[r.prompt_len:]))
    migrated = EVENT_LOG.recent(event="migrated")
    assert len(migrated) == 1
    assert migrated[0]["request_id"] == handles[0].rid
    assert migrated[0]["from_replica"] == src.id
    assert migrated[0]["to_replica"] == dst_id
    snap = router.snapshot()
    assert snap["router"]["migrations_total"] == 1
    assert snap["shipments_in_flight"] == []
    spans = [e for e in router.trace.chrome_trace()["traceEvents"]
             if e["name"] == "migrate"]
    assert len(spans) == 1 and spans[0]["args"]["to"] == dst_id


def test_migration_chaos_leak_caught_and_attributed(tiny):
    """A chaos-injected block leak at the extract's slot release is the
    exact hazard the shipment ledger exists for: the source sanitizer
    must fail loudly and name the leaked block's last owner."""
    cfg, params = tiny
    spec = dict(prompt=_prompts(cfg, 1, seed=29)[0], max_new_tokens=32,
                seed=0, use_eos_stop=False)
    stream = []
    router = build_cluster(
        cfg, params,
        EngineConfig(max_batch_size=1, max_seq_len=64, max_queue_size=8,
                     sanitize=True),
        replicas=2).start()
    try:
        [h] = router.submit_many([dict(spec, on_token=lambda t:
                                       stream.append(int(t)))])
        _wait_tokens(stream, 2)
        rid = h.rid
        src = h._rr.replica
        dst_id = next(r.id for r in router.replicas if r is not src)
        src.engine.pause()
        try:
            chaos().leak_kv_blocks("slots-release", times=1)
            assert router.migrate_request(h, to_replica_id=dst_id)
        finally:
            src.engine.resume()
        # the request itself survives on the destination, token-complete
        res = h.result(120)
        assert len(res.tokens) == res.prompt_len + 32
        # the source scheduler's next ledger audit catches the leak
        deadline = time.perf_counter() + 30
        while src.engine._scheduler_error is None:
            assert time.perf_counter() < deadline, \
                "sanitizer did not catch the leaked block"
            time.sleep(0.01)
        err = src.engine._scheduler_error
        assert isinstance(err, LedgerError)
        assert "leaked reference" in str(err)
        assert rid in str(err), \
            f"leak not attributed to its last owner: {err}"
    finally:
        chaos().reset()
        router.shutdown()
    # the shutdown leak report names the same block with its last owners
    report = src.engine.sanitizer_report
    assert report and any(rid in owner
                          for leak in report
                          for owner in leak["last_owners"])


def test_sanitizer_bounds_unreconciled_shipments(tiny):
    """A shipment ledger that only ever grows (end_ship missing) is a
    silent leak factory; the per-iteration audit fails once in-flight
    shipments exceed the slot count."""
    cfg, params = tiny
    engine = ServingEngine(
        cfg, params,
        EngineConfig(max_batch_size=2, max_seq_len=64,
                     sanitize=True)).start()
    try:
        _run(engine, [dict(prompt=[1, 2, 3], max_new_tokens=2,
                           use_eos_stop=False)])
        pool = engine.slots.pool
        engine.call_in_scheduler(lambda: [
            pool.begin_ship(f"ship-zombie-{i}", f"req-{i}", [], 0)
            for i in range(engine.slots.num_slots + 1)])
        deadline = time.perf_counter() + 30
        while engine._scheduler_error is None:
            assert time.perf_counter() < deadline
            time.sleep(0.01)
        assert "end_ship missing" in str(engine._scheduler_error)
    finally:
        engine.shutdown()


# ---------------------------------------------------------------------------
# server surface: --disagg wiring, GET /cluster roles + in-flight shipments
# ---------------------------------------------------------------------------

def test_generation_service_disagg_surface(tiny):
    from megatron_llm_tpu.generation.server import GenerationService
    from megatron_llm_tpu.tokenizer.tokenizer import NullTokenizer

    cfg, params = tiny
    svc = GenerationService(cfg, params,
                            NullTokenizer(vocab_size=cfg.vocab_size),
                            max_batch_size=2, engine_max_seq_len=64,
                            disagg="1:1")
    try:
        status, resp = svc.handle({"prompts": ["3 4 5", "6 7 8"],
                                   "tokens_to_generate": 4,
                                   "random_seed": 7})
        assert status == 200
        assert len(resp["text"]) == 2
        snap = svc.cluster_snapshot()
        assert snap["router"]["roles"] == {"prefill": 1, "decode": 1}
        assert snap["router"]["ships_total"] == 2
        assert snap["shipments_in_flight"] == []
        assert {r["role"] for r in snap["replicas"]} == \
            {"prefill", "decode"}
    finally:
        svc.close()


def test_parse_disagg_validation():
    from megatron_llm_tpu.generation.server import GenerationService

    assert GenerationService._parse_disagg("2:1") == (2, 1)
    for bad in ("2", "a:b", "0:1", "1:0", ":", "1:2:3"):
        with pytest.raises(ValueError):
            GenerationService._parse_disagg(bad)
