"""Serving metrics registry: counters, gauges, latency histograms, and the
tensorboard-style export (same fake-writer idiom as the training metrics
tests)."""

from megatron_llm_tpu.serving import LatencyHistogram, ServingMetrics


class FakeWriter:
    def __init__(self):
        self.scalars = {}

    def add_scalar(self, name, value, iteration):
        self.scalars[name] = (value, iteration)


def test_histogram_stats():
    h = LatencyHistogram(max_samples=4)
    for x in (1.0, 2.0, 3.0, 4.0):
        h.observe(x)
    assert h.count == 4
    assert h.mean() == 2.5
    assert h.percentile(0) == 1.0
    assert h.percentile(100) == 4.0
    # the sample window is bounded, and the mean covers the SAME window
    # as the percentiles; all-time aggregates live in total_count/total
    h.observe(5.0)
    assert h.count == 5 and h.total_count == 5 and h.window_count == 4
    assert h.mean() == 3.5  # mean over the retained window [2, 3, 4, 5]
    assert h.total == 15.0
    assert h.percentile(0) == 2.0  # 1.0 evicted from the window
    snap = h.snapshot()
    assert snap["count"] == 4 and snap["total_count"] == 5
    assert snap["mean_s"] == 3.5


def test_empty_histogram():
    h = LatencyHistogram()
    assert h.count == 0 and h.mean() == 0.0 and h.percentile(95) == 0.0
    assert h.snapshot() == {"count": 0, "total_count": 0, "mean_s": 0.0,
                            "p50_s": 0.0, "p95_s": 0.0, "p99_s": 0.0}
    # unitless reservoirs (prefix_hit_tokens) share the same helper with
    # an empty suffix
    assert h.snapshot(suffix="") == {"count": 0, "total_count": 0,
                                     "mean": 0.0, "p50": 0.0, "p95": 0.0,
                                     "p99": 0.0}


def test_counters_gauges_and_decode_stats():
    m = ServingMetrics(num_slots=4)
    m.inc("submitted", by=3)
    m.inc("completed")
    m.set_gauges(slots_active=2, queue_depth=5)
    m.observe_decode_iteration(3, 0.01)
    m.observe_decode_iteration(2, 0.01)
    snap = m.snapshot()
    assert snap["submitted"] == 3 and snap["completed"] == 1
    assert snap["running"] == 2 and snap["queued"] == 5
    assert snap["slots_total"] == 4 and snap["slot_occupancy"] == 0.5
    assert snap["decode_iterations"] == 2
    assert snap["decode_tokens"] == 5  # 3 + 2 slots served
    assert snap["max_decode_batch"] == 3
    assert snap["per_token_latency"]["count"] == 5


def test_prefix_cache_counters_and_hit_rate():
    m = ServingMetrics(num_slots=2)
    snap = m.snapshot()
    assert snap["prefix_hits"] == 0 and snap["prefix_hit_rate"] == 0.0
    m.inc("prefix_hits", by=3)
    m.inc("prefix_misses")
    m.inc("prefix_evicted_blocks", by=7)
    m.set_gauges(prefix_blocks=12)
    for n in (64, 64, 128):
        m.observe_prefix_hit_tokens(n)
    snap = m.snapshot()
    assert snap["prefix_hits"] == 3 and snap["prefix_misses"] == 1
    assert snap["prefix_hit_rate"] == 0.75
    assert snap["prefix_evicted_blocks"] == 7
    assert snap["prefix_blocks"] == 12
    hist = snap["prefix_hit_tokens"]
    assert hist["count"] == 3 and hist["mean"] == 256.0 / 3
    assert hist["p50"] == 64.0


def test_write_exports_serving_scalars():
    m = ServingMetrics(num_slots=2)
    m.inc("submitted")
    m.inc("rejected_queue_full", by=2)
    m.observe_ttft(0.5)
    m.observe_decode_iteration(2, 0.1)
    w = FakeWriter()
    m.write(w, iteration=7)
    assert w.scalars["serving/submitted"] == (1, 7)
    assert w.scalars["serving/rejected_queue_full"] == (2, 7)
    assert w.scalars["serving/max_decode_batch"] == (2, 7)
    assert w.scalars["serving/ttft_mean_s"] == (0.5, 7)
    assert w.scalars["serving/slot_occupancy"] == (0.0, 7)
    for key in ("serving/running", "serving/queued",
                "serving/per_token_latency_p95_s",
                "serving/e2e_latency_mean_s",
                "serving/prefix_hits", "serving/prefix_misses",
                "serving/prefix_hit_rate", "serving/prefix_blocks",
                "serving/prefix_hit_tokens_mean"):
        assert key in w.scalars
