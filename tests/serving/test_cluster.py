"""Multi-chip serving tests (CPU, 8 virtual devices, tiny model).

Two contracts, each load-bearing for serving/cluster/:

- **sharded-engine parity** — a tp=2 engine (params in the serving
  re-layout on a 2-device submesh, head-sharded paged pool, replicated
  block tables) must produce bitwise-identical tokens to the single-chip
  engine across fp32/int8-kv × pipelined/classic decode, with zero
  post-warmup recompiles.
- **router failover** — draining or killing a replica mid-stream loses
  no accepted request: pulled-back and resubmitted requests replay their
  per-request seed and the client-visible tokens are bitwise-equal to an
  uninterrupted single-engine run, with the block-pool ledger sanitizer
  balanced on every replica and the router's EVENT_LOG lines correlated
  by request id.
"""

import dataclasses
import time

import jax
import numpy as np
import pytest

from megatron_llm_tpu.analysis.sanitizers import no_recompiles
from megatron_llm_tpu.config import ParallelConfig, tiny_config
from megatron_llm_tpu.models import model as model_lib
from megatron_llm_tpu.obs.logging import EVENT_LOG
from megatron_llm_tpu.parallel import mesh as mesh_lib
from megatron_llm_tpu.serving import (
    EngineConfig,
    QueueFull,
    Router,
    RouterConfig,
    ServingEngine,
    build_cluster,
    build_sharded_engine,
)


@pytest.fixture(scope="module")
def tiny():
    cfg = tiny_config(num_layers=2, vocab_size=64,
                      make_vocab_size_divisible_by=8)
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


def _prompts(cfg, n, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, cfg.vocab_size,
                         int(rng.integers(4, 12))).tolist()
            for _ in range(n)]


def _run(engine_or_router, specs, timeout=120):
    handles = engine_or_router.submit_many(specs)
    return [h.result(timeout) for h in handles]


def _reference_tokens(cfg, params, specs, **cfg_overrides):
    """Uninterrupted single-chip engine run — the parity baseline."""
    kw = dict(max_batch_size=2, max_seq_len=64, max_queue_size=32)
    kw.update(cfg_overrides)
    engine = ServingEngine(cfg, params, EngineConfig(**kw)).start()
    try:
        return [list(r.tokens) for r in _run(engine, specs)]
    finally:
        engine.shutdown()


# ---------------------------------------------------------------------------
# sharded engine: tp=2 bitwise parity + zero recompiles
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kv_quant", ["none", "int8"])
@pytest.mark.parametrize("pipeline", [True, False],
                         ids=["pipelined", "classic"])
def test_sharded_engine_bitwise_matches_single_chip(tiny, devices,
                                                    kv_quant, pipeline):
    cfg, params = tiny
    if kv_quant != "none":
        cfg = dataclasses.replace(cfg, kv_cache_quant=kv_quant).validate()
    specs = [dict(prompt=p, max_new_tokens=10, seed=i, use_eos_stop=False)
             for i, p in enumerate(_prompts(cfg, 3))]
    # prefill_bucket=16 pins one prefill shape over the ragged prompts,
    # so the post-warmup window genuinely exercises zero-recompile
    ref = _reference_tokens(cfg, params, specs, prefill_bucket=16,
                            pipeline_decode=pipeline)

    engine = build_sharded_engine(
        cfg, params,
        EngineConfig(max_batch_size=2, max_seq_len=64, max_queue_size=32,
                     prefill_bucket=16, pipeline_decode=pipeline),
        parallel=ParallelConfig(tensor_parallel=2),
        devices=devices[:2])
    assert engine.mesh is not None
    try:
        engine.start()
        # warmup runs the full workload shape once: prefill bucket,
        # decode step, AND the queued-admission-mid-decode merge (3
        # requests through 2 slots) all compile on the submesh here
        _run(engine, specs)
        with no_recompiles():
            got = [list(r.tokens) for r in _run(engine, specs)]
    finally:
        engine.shutdown()
    assert got == ref


def test_sharded_params_are_actually_sharded(tiny, devices):
    cfg, params = tiny
    engine = build_sharded_engine(
        cfg, params, EngineConfig(max_batch_size=2, max_seq_len=64),
        parallel=ParallelConfig(tensor_parallel=2), devices=devices[:2])
    total = sum(np.asarray(l).nbytes for l in jax.tree.leaves(params))
    per_dev = sum(l.addressable_shards[0].data.nbytes
                  for l in jax.tree.leaves(engine.params))
    # the serving re-layout shards the big projections 2-way; small
    # replicated leaves (norms, biases) keep this above exactly 0.5
    assert per_dev < 0.75 * total


def test_replica_submeshes_disjoint():
    meshes = mesh_lib.replica_submeshes(
        ParallelConfig(tensor_parallel=2), 2)
    assert len(meshes) == 2
    seen = [frozenset(d.id for d in m.devices.flatten()) for m in meshes]
    assert all(len(s) == 2 for s in seen)
    assert not (seen[0] & seen[1]), "replica submeshes must be disjoint"
    with pytest.raises(ValueError):
        mesh_lib.replica_submeshes(ParallelConfig(tensor_parallel=8), 2)


# ---------------------------------------------------------------------------
# router: dispatch, stickiness, health surface
# ---------------------------------------------------------------------------

def test_router_spreads_load_and_honors_sticky(tiny):
    cfg, params = tiny
    specs = [dict(prompt=p, max_new_tokens=6, seed=i, use_eos_stop=False)
             for i, p in enumerate(_prompts(cfg, 4))]
    router = build_cluster(cfg, params,
                           EngineConfig(max_batch_size=2, max_seq_len=64),
                           replicas=2).start()
    try:
        ref = _reference_tokens(cfg, params, specs)
        got = [list(r.tokens) for r in _run(router, specs)]
        assert got == ref
        snap = router.snapshot()
        assert snap["router"]["routed_total"] == 4
        assert snap["router"]["completed_total"] == 4
        # an idle 2-replica cluster splits a 4-burst across both
        assert all(r["dispatched"] >= 1 for r in snap["replicas"])
        # sticky: same key keeps landing on one replica
        sticky = [dict(prompt=specs[0]["prompt"], max_new_tokens=4,
                       seed=9, use_eos_stop=False, sticky_key="conv-1")
                  for _ in range(3)]
        hs = router.submit_many(sticky)
        # rr.replica only changes on failover; none happens here
        replicas = {h._rr.replica.id for h in hs}
        for h in hs:
            h.result(120)
        assert len(replicas) == 1
    finally:
        router.shutdown()


def test_router_rejects_when_all_draining(tiny):
    cfg, params = tiny
    router = build_cluster(cfg, params,
                           EngineConfig(max_batch_size=2, max_seq_len=64),
                           replicas=2).start()
    try:
        router.drain(timeout=60)
        with pytest.raises(QueueFull):
            router.submit_many([dict(prompt=[1, 2, 3], max_new_tokens=2)])
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# failover: drain and kill, bitwise parity, ledger balance, event log
# ---------------------------------------------------------------------------

def test_drain_replica_mid_stream_loses_nothing(tiny):
    cfg, params = tiny
    n = 6
    base = _prompts(cfg, 4, seed=3)
    specs = [dict(prompt=base[i % 4], max_new_tokens=10, seed=i,
                  use_eos_stop=False) for i in range(n)]
    ref = _reference_tokens(cfg, params, specs)

    EVENT_LOG.clear()
    streams = {i: [] for i in range(n)}
    # 1 slot per replica forces a queue on each: the drain has
    # not-yet-started requests to pull back and resubmit
    router = build_cluster(
        cfg, params,
        EngineConfig(max_batch_size=1, max_seq_len=64, max_queue_size=32,
                     sanitize=True),
        replicas=2).start()
    try:
        handles = router.submit_many([
            dict(s, on_token=(lambda i: (lambda t:
                 streams[i].append(int(t))))(i))
            for i, s in enumerate(specs)])
        time.sleep(0.2)  # let decode start on both replicas
        assert router.drain_replica("replica-0", timeout=120)
        results = [h.result(120) for h in handles]
    finally:
        for rep in router.replicas:
            assert rep.engine.sanitizer_report == []
        router.shutdown()

    # no accepted request lost, every trajectory bitwise-equal to the
    # uninterrupted run, and the client streams saw exactly the
    # generated suffix once (replayed prefixes suppressed)
    got = [list(r.tokens) for r in results]
    assert got == ref
    for i, r in enumerate(results):
        assert streams[i] == list(map(int, r.tokens[r.prompt_len:]))

    drained = EVENT_LOG.recent(event="replica_drained")
    assert drained and drained[-1]["replica"] == "replica-0"
    routed_ids = {e["request_id"]
                  for e in EVENT_LOG.recent(event="routed")}
    for e in EVENT_LOG.recent(event="resubmitted"):
        # failover lines carry the new engine-assigned id and link the
        # old one, so the hop is traceable end to end
        assert e["request_id"] and e["prev_request_id"] in routed_ids
        assert e["from_replica"] == "replica-0"


def test_kill_replica_mid_stream_loses_nothing(tiny):
    cfg, params = tiny
    n = 6
    base = _prompts(cfg, 4, seed=5)
    specs = [dict(prompt=base[i % 4], max_new_tokens=10, seed=i,
                  use_eos_stop=False) for i in range(n)]
    ref = _reference_tokens(cfg, params, specs)

    EVENT_LOG.clear()
    router = build_cluster(
        cfg, params,
        EngineConfig(max_batch_size=1, max_seq_len=64, max_queue_size=32),
        replicas=2).start()
    try:
        handles = router.submit_many(specs)
        time.sleep(0.15)
        moved = router.kill_replica("replica-0")
        assert moved >= 1, "the kill should orphan in-flight requests"
        got = [list(h.result(120).tokens) for h in handles]
    finally:
        router.shutdown()
    assert got == ref
    assert EVENT_LOG.recent(event="replica_dead")
    assert router.snapshot()["router"]["failovers_total"] >= moved


def test_probe_thread_detects_dead_scheduler(tiny):
    """A replica whose scheduler thread dies (not via kill_replica) is
    spotted by the health probe and its requests fail over."""
    cfg, params = tiny
    specs = [dict(prompt=p, max_new_tokens=8, seed=i, use_eos_stop=False)
             for i, p in enumerate(_prompts(cfg, 2, seed=7))]
    ref = _reference_tokens(cfg, params, specs)
    router = build_cluster(
        cfg, params,
        EngineConfig(max_batch_size=1, max_seq_len=64, max_queue_size=32),
        replicas=2,
        router_config=RouterConfig(probe_interval_s=0.02)).start()
    try:
        # simulate a crash: stop replica-1's scheduler out from under the
        # router (shutdown() joins the thread; requests stay unfinished)
        victim = router.replicas[1]
        handles = router.submit_many(specs)
        victim.engine.shutdown(timeout=30)
        got = [list(h.result(120).tokens) for h in handles]
        assert got == ref
        assert victim.dead
    finally:
        router.shutdown()


def test_sharded_replicas_behind_router(tiny, devices):
    """The composed topology: 2 replicas x tp=2 on disjoint submeshes,
    routed traffic bitwise-equal to the single-chip engine."""
    cfg, params = tiny
    specs = [dict(prompt=p, max_new_tokens=8, seed=i, use_eos_stop=False)
             for i, p in enumerate(_prompts(cfg, 4, seed=11))]
    ref = _reference_tokens(cfg, params, specs)
    router = build_cluster(cfg, params,
                           EngineConfig(max_batch_size=2, max_seq_len=64),
                           replicas=2,
                           parallel=ParallelConfig(tensor_parallel=2))
    assert isinstance(router, Router)
    meshes = [r.engine.mesh for r in router.replicas]
    assert all(m is not None for m in meshes)
    ids = [frozenset(d.id for d in m.devices.flatten()) for m in meshes]
    assert not (ids[0] & ids[1])
    router.start()
    try:
        got = [list(r.tokens) for r in _run(router, specs)]
    finally:
        router.shutdown()
    assert got == ref


# ---------------------------------------------------------------------------
# server surface
# ---------------------------------------------------------------------------

def test_generation_service_cluster_surface(tiny):
    from megatron_llm_tpu.generation.server import GenerationService
    from megatron_llm_tpu.tokenizer.tokenizer import NullTokenizer

    cfg, params = tiny
    svc = GenerationService(cfg, params,
                            NullTokenizer(vocab_size=cfg.vocab_size),
                            max_batch_size=2, engine_max_seq_len=64,
                            replicas=2, router=True)
    try:
        status, resp = svc.handle({"prompts": ["3 4 5", "6 7 8"],
                                   "tokens_to_generate": 4,
                                   "random_seed": 7})
        assert status == 200
        assert len(resp["text"]) == 2 and resp["request_ids"]
        snap = svc.cluster_snapshot()
        assert snap["router"]["replicas"] == 2
        assert snap["router"]["completed_total"] == 2
        assert {r["id"] for r in snap["replicas"]} == \
            {"replica-0", "replica-1"}
        assert all(r["alive"] for r in snap["replicas"])
    finally:
        svc.close()


def test_single_engine_cluster_snapshot(tiny):
    from megatron_llm_tpu.generation.server import GenerationService
    from megatron_llm_tpu.tokenizer.tokenizer import NullTokenizer

    cfg, params = tiny
    svc = GenerationService(cfg, params,
                            NullTokenizer(vocab_size=cfg.vocab_size),
                            max_batch_size=2, engine_max_seq_len=64)
    try:
        # never-created engine: empty view, no slot cache allocated
        assert svc.cluster_snapshot() == {"router": None, "replicas": []}
        status, _ = svc.handle({"prompts": ["3 4 5"],
                                "tokens_to_generate": 2})
        assert status == 200
        snap = svc.cluster_snapshot()
        assert snap["router"] is None
        assert snap["replicas"][0]["alive"]
    finally:
        svc.close()
