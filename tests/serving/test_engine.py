"""Continuous-batching engine tests (CPU, tiny model).

The load-bearing test is ``test_continuous_batching_matches_one_shot``:
eight staggered ragged requests through a 4-slot engine must return,
per prompt, exactly the tokens the one-shot ``generate_tokens`` path
produces (the pre-engine server trajectory), AND at least two requests
must have shared a decode iteration (``max_decode_batch``) — the direct
evidence of batching rather than serialization.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.config import tiny_config
from megatron_llm_tpu.generation import generate_tokens, score_tokens
from megatron_llm_tpu.models import model as model_lib
from megatron_llm_tpu.serving import EngineConfig, QueueFull, ServingEngine


@pytest.fixture(scope="module")
def tiny():
    cfg = tiny_config(num_layers=2, vocab_size=64,
                      make_vocab_size_divisible_by=8)
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


def _engine(cfg, params, **overrides):
    kw = dict(max_batch_size=4, max_seq_len=64, max_queue_size=16)
    kw.update(overrides)
    return ServingEngine(cfg, params, EngineConfig(**kw))


def _reference(cfg, params, prompt, max_new):
    """One-shot greedy rollout for a single prompt — the trajectory the
    server produced before the engine existed."""
    total = len(prompt) + max_new
    toks = np.zeros((1, total), np.int32)
    toks[0, :len(prompt)] = prompt
    out = generate_tokens(cfg, params, jnp.asarray(toks),
                          jnp.asarray([len(prompt)], jnp.int32),
                          eos_id=-1, use_eos_stop=False)
    return np.asarray(out.tokens)[0].tolist()


def test_continuous_batching_matches_one_shot(tiny):
    cfg, params = tiny
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, cfg.vocab_size,
                            int(rng.integers(3, 11))).tolist()
               for _ in range(8)]
    max_new = 12
    engine = _engine(cfg, params).start()
    try:
        handles = []
        for p in prompts:  # staggered arrivals
            handles.append(engine.submit(p, max_new_tokens=max_new,
                                         use_eos_stop=False))
            time.sleep(0.002)
        results = [h.result(timeout=600) for h in handles]
    finally:
        engine.shutdown()

    for p, r in zip(prompts, results):
        assert r.finish_reason == "length"
        assert r.prompt_len == len(p)
        assert r.tokens == _reference(cfg, params, p, max_new)

    snap = engine.metrics.snapshot()
    assert snap["completed"] == 8
    assert snap["admitted"] == 8 and snap["prefills"] == 8
    # ≥ 2 requests decoded in the same batch iteration = true continuous
    # batching (8 requests over 4 slots would serialize otherwise)
    assert snap["max_decode_batch"] >= 2


def test_engine_logprobs_match_score(tiny):
    """Engine-reported logprobs (prompt positions + generated tokens) must
    equal post-hoc scoring of the final sequence, the same invariant
    test_generation.py::test_logprobs_match_score checks for the one-shot
    loop."""
    cfg, params = tiny
    engine = _engine(cfg, params).start()
    try:
        r = engine.submit([5, 9, 3, 7], max_new_tokens=5,
                          use_eos_stop=False,
                          return_logprobs=True).result(timeout=600)
    finally:
        engine.shutdown()
    assert len(r.logprobs) == len(r.tokens) - 1
    scored = np.asarray(score_tokens(
        cfg, params, jnp.asarray([r.tokens], jnp.int32)))[0]
    np.testing.assert_allclose(r.logprobs, scored, atol=2e-4, rtol=2e-4)


def test_slot_reuse_across_staggered_arrivals(tiny):
    """Five requests through two slots: every slot must be recycled and
    every request completed."""
    cfg, params = tiny
    engine = _engine(cfg, params, max_batch_size=2).start()
    try:
        handles = [engine.submit([3 + i, 7, 11], max_new_tokens=6,
                                 use_eos_stop=False) for i in range(5)]
        results = [h.result(timeout=600) for h in handles]
    finally:
        engine.shutdown()
    assert all(r.finish_reason == "length" for r in results)
    snap = engine.metrics.snapshot()
    assert snap["admitted"] == 5 and snap["completed"] == 5
    assert snap["max_decode_batch"] <= 2  # only two slots exist
    assert engine.slots.free_slots == 2   # all returned to the free list


def test_eos_retires_mid_batch(tiny):
    """One request hitting EOS must leave the batch alone: the other
    request keeps decoding to its full budget."""
    cfg, params = tiny
    prompt = [5, 9, 3]
    ref = _reference(cfg, params, prompt, 8)
    gen = ref[len(prompt):]
    eos = gen[2]  # a token the greedy rollout actually emits
    other = [7, 8, 9, 10]
    engine = _engine(cfg, params).start()
    try:
        engine.pause()  # both requests enter the batch together
        ha = engine.submit(prompt, max_new_tokens=8, eos_id=eos)
        hb = engine.submit(other, max_new_tokens=8, use_eos_stop=False)
        engine.resume()
        ra = ha.result(timeout=600)
        rb = hb.result(timeout=600)
    finally:
        engine.shutdown()
    assert ra.finish_reason == "eos"
    stop = gen.index(eos) + 1  # generation stops AT the EOS token
    assert ra.tokens == ref[:len(prompt) + stop]
    assert rb.finish_reason == "length"
    assert rb.tokens == _reference(cfg, params, other, 8)


def test_cancel_queued_request(tiny):
    cfg, params = tiny
    engine = _engine(cfg, params).start()
    engine.pause()  # keep it queued
    try:
        h = engine.submit([5, 9, 3], max_new_tokens=4)
        h.cancel()
        r = h.result(timeout=60)
    finally:
        engine.shutdown()
    assert r.finish_reason == "cancelled"
    assert r.tokens == [5, 9, 3]  # nothing generated
    assert engine.metrics.snapshot()["cancelled"] == 1


def test_cancel_running_request(tiny):
    """Cancellation of an in-flight request lands at an iteration boundary:
    some tokens generated, far fewer than the budget."""
    cfg, params = tiny
    got_first = threading.Event()

    def on_token(tok):
        got_first.set()
        time.sleep(0.02)  # throttle decode so the cancel lands mid-flight

    engine = _engine(cfg, params).start()
    try:
        h = engine.submit([5, 9, 3], max_new_tokens=50, use_eos_stop=False,
                          on_token=on_token)
        assert got_first.wait(timeout=300)
        h.cancel()
        r = h.result(timeout=60)
    finally:
        engine.shutdown()
    assert r.finish_reason == "cancelled"
    assert 1 <= len(r.tokens) - r.prompt_len < 50
    # the slot went back to the free list
    assert engine.slots.free_slots == 4


def test_streaming_callback_order(tiny):
    cfg, params = tiny
    streamed = []
    engine = _engine(cfg, params).start()
    try:
        r = engine.submit([5, 9, 3], max_new_tokens=6, use_eos_stop=False,
                          on_token=streamed.append).result(timeout=600)
    finally:
        engine.shutdown()
    assert streamed == r.tokens[r.prompt_len:]


def test_sampled_trajectory_independent_of_batch(tiny):
    """A seeded sampled request must produce the same tokens whether it
    runs alone (slot 0) or lands in a different slot alongside greedy
    companions — the per-request RNG stream is folded on the request's own
    token counter, never on batch state."""
    cfg, params = tiny
    spec = dict(prompt=[5, 9, 3], max_new_tokens=8, use_eos_stop=False,
                temperature=0.8, top_k=8, seed=123)
    engine = _engine(cfg, params).start()
    try:
        alone = engine.submit(**spec).result(timeout=600)
        engine.pause()  # companions admitted first → spec lands in slot 3
        comps = [engine.submit([7 + i, 11], max_new_tokens=8,
                               use_eos_stop=False) for i in range(3)]
        h = engine.submit(**spec)
        engine.resume()
        shared = h.result(timeout=600)
        for c in comps:
            c.result(timeout=600)
        reseeded = engine.submit(**{**spec, "seed": 124}).result(timeout=600)
    finally:
        engine.shutdown()
    assert shared.tokens == alone.tokens
    assert reseeded.tokens != alone.tokens  # overwhelmingly


def test_admission_validation(tiny):
    cfg, params = tiny
    engine = _engine(cfg, params)
    try:
        with pytest.raises(ValueError, match="empty prompt"):
            engine.submit([], max_new_tokens=4)
        with pytest.raises(ValueError, match="max_new_tokens"):
            engine.submit([5], max_new_tokens=0)
        with pytest.raises(ValueError, match="sequence budget"):
            engine.submit(list(range(1, 61)), max_new_tokens=5)  # 60+5 > 64
        assert engine.metrics.snapshot()["rejected_invalid"] == 3
    finally:
        engine.shutdown()


def test_queue_full_backpressure(tiny):
    cfg, params = tiny
    engine = _engine(cfg, params, max_batch_size=1, max_queue_size=2,
                     retry_after_s=3.0).start()
    engine.pause()  # nothing drains: deterministic queue pressure
    try:
        engine.submit([5], max_new_tokens=2)
        engine.submit([6], max_new_tokens=2)
        with pytest.raises(QueueFull) as ei:
            engine.submit([7], max_new_tokens=2)
        assert ei.value.retry_after_s == 3.0
        snap = engine.metrics.snapshot()
        assert snap["rejected_queue_full"] == 1
        assert snap["queued"] == 2
    finally:
        engine.shutdown()


def test_scheduler_failure_during_prefill_fails_request(tiny):
    """A crash while a request is mid-admission (popped from the queue but
    not yet slotted) must still fail THAT request — it is in neither the
    queue nor the active set at that moment."""
    import megatron_llm_tpu.serving.engine as engine_mod
    cfg, params = tiny

    def boom(*args, **kwargs):
        raise RuntimeError("injected prefill failure")

    orig = engine_mod._prefill_impl
    engine_mod._prefill_impl = boom
    engine = _engine(cfg, params)
    try:
        engine.start()
        h = engine.submit([5, 9, 3], max_new_tokens=4)
        with pytest.raises(RuntimeError, match="scheduler failed"):
            h.result(timeout=300)
    finally:
        engine_mod._prefill_impl = orig
        engine.shutdown()


def test_scheduler_failure_fails_requests_loudly(tiny):
    """A dead scheduler must not leave result() blocked forever: in-flight
    requests finish with reason "error" and result() raises."""
    cfg, params = tiny

    def boom(*args, **kwargs):
        raise RuntimeError("injected decode failure")

    engine = _engine(cfg, params)
    engine._decode = boom
    engine.start()
    try:
        h = engine.submit([5, 9, 3], max_new_tokens=8, use_eos_stop=False)
        with pytest.raises(RuntimeError, match="scheduler failed"):
            h.result(timeout=300)
        assert h.done()
    finally:
        engine.shutdown()


class TestPagedEquivalence:
    """The paged acceptance matrix (docs/serving.md, 'Paged KV cache'):
    with a SMALL block size — mixed-length requests spanning many blocks,
    lazy decode-time growth crossing block boundaries, zero-copy prefix
    hits — every committed token must equal the one-shot
    ``generate_tokens`` trajectory bitwise.  fp32 and fully-int8, whole-
    prompt and chunked admission, pipelined decode on and off; plus the
    degenerate fixed-stride configuration (``kv_block_size ==
    max_seq_len``), which must be the same code path with one block per
    slot."""

    @pytest.fixture(scope="class")
    def tiny_int8(self, tiny):
        import dataclasses

        from megatron_llm_tpu.ops.quant import quantize_params

        cfg, params = tiny
        return (dataclasses.replace(cfg, kv_cache_quant="int8"),
                quantize_params(params))

    def _drive(self, cfg, params, **overrides):
        """Mixed-length ragged batch through a paged engine; returns the
        results plus a metrics snapshot."""
        rng = np.random.default_rng(23)
        prompts = [rng.integers(1, cfg.vocab_size, n).tolist()
                   for n in (3, 17, 30, 9)]  # 1..4 blocks at bk=8
        max_news = [20, 9, 14, 5]            # growth crosses boundaries
        kw = dict(max_batch_size=4, max_seq_len=64, max_queue_size=16,
                  kv_block_size=8)
        kw.update(overrides)
        engine = ServingEngine(cfg, params, EngineConfig(**kw)).start()
        try:
            handles = [engine.submit(p, max_new_tokens=n,
                                     use_eos_stop=False)
                       for p, n in zip(prompts, max_news)]
            results = [h.result(timeout=600) for h in handles]
        finally:
            engine.shutdown()
        for p, n, r in zip(prompts, max_news, results):
            assert r.finish_reason == "length"
            assert r.tokens == _reference(cfg, params, p, n)
        return engine.metrics.snapshot()

    @pytest.mark.parametrize("pipeline", [True, False],
                             ids=["pipelined", "sync"])
    def test_fp32_whole_prompt(self, tiny, pipeline):
        snap = self._drive(*tiny, pipeline_decode=pipeline)
        assert snap["max_decode_batch"] >= 2
        assert snap["blocks_used"] >= 0 and snap["blocks_free"] >= 0

    @pytest.mark.parametrize("pipeline", [True, False],
                             ids=["pipelined", "sync"])
    def test_fp32_chunked_admission(self, tiny, pipeline):
        snap = self._drive(*tiny, prefill_chunk=8,
                           pipeline_decode=pipeline)
        assert snap["prefill_chunks"] > 4  # really ran chunk-at-a-time

    def test_int8_whole_prompt(self, tiny_int8):
        self._drive(*tiny_int8)

    def test_int8_chunked_pipelined(self, tiny_int8):
        self._drive(*tiny_int8, prefill_chunk=8, pipeline_decode=True)

    def test_fixed_stride_degenerate_block(self, tiny):
        """kv_block_size == max_seq_len: one block per slot — the
        pre-paging layout expressed in the same engine code path."""
        snap = self._drive(*tiny, kv_block_size=64)
        assert snap["blocks_used"] <= 4 + 1  # <= one block per slot

    def test_prefix_hit_with_small_blocks(self, tiny):
        """Zero-copy sharing under real paging: sequential shared-prefix
        requests hit and stay bitwise equal, with no COW copies."""
        cfg, params = tiny
        rng = np.random.default_rng(29)
        prompt = rng.integers(1, cfg.vocab_size, 21).tolist()
        engine = ServingEngine(cfg, params, EngineConfig(
            max_batch_size=2, max_seq_len=64, max_queue_size=8,
            kv_block_size=8, prefix_cache_blocks=16)).start()
        try:
            a = engine.submit(prompt, max_new_tokens=10,
                              use_eos_stop=False).result(timeout=600)
            b = engine.submit(prompt, max_new_tokens=10,
                              use_eos_stop=False).result(timeout=600)
        finally:
            engine.shutdown()
        ref = _reference(cfg, params, prompt, 10)
        assert a.tokens == ref and b.tokens == ref
        snap = engine.metrics.snapshot()
        assert snap["prefix_hits"] == 1
        assert snap["cow_copies_total"] == 0

    def test_pool_exhaustion_parks_and_recovers(self, tiny):
        """A pool too small for all requests at once: admission parks at
        the queue head until retirements free blocks — every request
        still completes with the exact one-shot trajectory (FIFO, no
        deadlock, no corruption)."""
        cfg, params = tiny
        rng = np.random.default_rng(31)
        prompts = [rng.integers(1, cfg.vocab_size, 16).tolist()
                   for _ in range(5)]
        # 9 usable blocks of 8 = 72 tokens; each request needs
        # ceil((16+8)/8) = 3 blocks, so at most 3 can run concurrently
        engine = ServingEngine(cfg, params, EngineConfig(
            max_batch_size=5, max_seq_len=32, max_queue_size=8,
            kv_block_size=8, kv_pool_blocks=10)).start()
        try:
            handles = [engine.submit(p, max_new_tokens=8,
                                     use_eos_stop=False) for p in prompts]
            results = [h.result(timeout=600) for h in handles]
        finally:
            engine.shutdown()
        for p, r in zip(prompts, results):
            assert r.tokens == _reference(cfg, params, p, 8)
        snap = engine.metrics.snapshot()
        assert snap["max_decode_batch"] <= 3  # the pool really bounded it


class TestSpeculative:
    """Speculative decoding acceptance matrix (docs/serving.md,
    'Speculative decoding'): with per-slot prompt-lookup drafts, a
    batched variable-length verify step, and rollback over paged
    blocks, every committed token must equal the one-shot
    ``generate_tokens`` trajectory bitwise — spec on/off x fp32/int8 x
    paged/fixed-stride x pipelined/sync.  The repetitive prompts below
    are chosen so the random-init model settles into a cycle and the
    drafter actually engages (asserted via ``spec_steps``), so the
    accept-and-commit path — not just the gate — is what's equal."""

    REP_PROMPTS = [[5, 9, 3, 5, 9, 3, 5, 9, 3, 5, 9],
                   [7, 7, 7, 7, 7, 7, 7],
                   [4, 8, 2, 4, 8, 2, 4, 8],
                   [11, 6, 11, 6, 11, 6, 11]]
    MAX_NEW = 20

    @pytest.fixture(scope="class")
    def tiny_int8(self, tiny):
        import dataclasses

        from megatron_llm_tpu.ops.quant import quantize_params

        cfg, params = tiny
        return (dataclasses.replace(cfg, kv_cache_quant="int8"),
                quantize_params(params))

    def _drive(self, cfg, params, draft_len=3, prompts=None,
               max_new=None, **overrides):
        kw = dict(max_batch_size=4, max_seq_len=64, max_queue_size=16,
                  spec_draft_len=draft_len)
        kw.update(overrides)
        prompts = prompts or self.REP_PROMPTS
        max_new = max_new or self.MAX_NEW
        engine = ServingEngine(cfg, params, EngineConfig(**kw)).start()
        try:
            handles = [engine.submit(p, max_new_tokens=max_new,
                                     use_eos_stop=False) for p in prompts]
            results = [h.result(timeout=600) for h in handles]
        finally:
            engine.shutdown()
        return results, engine.metrics.snapshot()

    def _check(self, cfg, params, **overrides):
        results, snap = self._drive(cfg, params, **overrides)
        for p, r in zip(self.REP_PROMPTS, results):
            assert r.finish_reason == "length"
            assert r.tokens == _reference(cfg, params, p, self.MAX_NEW)
        assert snap["spec_steps"] > 0, "drafter never engaged"
        assert 0 < snap["spec_acceptance_rate"] <= 1
        assert 1 <= snap["accepted_tokens_per_step"]["mean"] <= \
            overrides.get("draft_len", 3) + 1
        return snap

    @pytest.mark.parametrize("pipeline", [True, False],
                             ids=["pipelined", "sync"])
    def test_fp32_paged(self, tiny, pipeline):
        self._check(*tiny, kv_block_size=8, pipeline_decode=pipeline)

    @pytest.mark.parametrize("pipeline", [True, False],
                             ids=["pipelined", "sync"])
    def test_fp32_fixed_stride(self, tiny, pipeline):
        """kv_block_size == max_seq_len: the pre-paging dense layout,
        same engine code path (one block per slot)."""
        self._check(*tiny, kv_block_size=64, pipeline_decode=pipeline)

    @pytest.mark.slow
    def test_int8_paged(self, tiny_int8):
        self._check(*tiny_int8, kv_block_size=8)

    def test_int8_fixed_stride_sync(self, tiny_int8):
        self._check(*tiny_int8, kv_block_size=64, pipeline_decode=False)

    @pytest.mark.slow
    def test_composes_with_chunked_prefill_and_prefix_cache(self, tiny):
        self._check(*tiny, kv_block_size=8, prefill_chunk=8,
                    prefix_cache_blocks=16)

    def test_sampled_riders_unchanged(self, tiny):
        """Sampled requests carry empty drafts but ride verify batches
        (position-0 sampling with the same seed/counter stream), so
        their trajectories must be bitwise identical spec on vs off."""
        cfg, params = tiny
        reqs = [dict(prompt=self.REP_PROMPTS[0], max_new_tokens=12,
                     temperature=0.8, top_k=8, seed=123,
                     use_eos_stop=False),
                dict(prompt=self.REP_PROMPTS[1], max_new_tokens=12,
                     use_eos_stop=False)]

        def run(draft_len):
            engine = ServingEngine(cfg, params, EngineConfig(
                max_batch_size=4, max_seq_len=64,
                spec_draft_len=draft_len)).start()
            try:
                hs = [engine.submit(**r) for r in reqs]
                toks = [h.result(timeout=600).tokens for h in hs]
            finally:
                engine.shutdown()
            return toks, engine.metrics.snapshot()

        on, snap = run(3)
        off, _ = run(0)
        assert on == off
        assert snap["spec_steps"] > 0  # the greedy rider did speculate

    def test_eos_mid_window(self, tiny):
        """EOS landing inside an accepted draft span: the request must
        stop at exactly the token plain decode stops at — the commit
        loop retires the slot mid-window and discards the rest."""
        cfg, params = tiny
        prompt = [9, 2, 9, 2, 9, 2, 9]
        ref = _reference(cfg, params, prompt, 20)
        eos = int(ref[-1])

        def run(draft_len):
            engine = ServingEngine(cfg, params, EngineConfig(
                max_batch_size=2, max_seq_len=64,
                spec_draft_len=draft_len)).start()
            try:
                return engine.submit(prompt, max_new_tokens=20,
                                     eos_id=eos,
                                     use_eos_stop=True).result(timeout=600)
            finally:
                engine.shutdown()

        r_on, r_off = run(4), run(0)
        assert r_on.tokens == r_off.tokens
        assert r_on.finish_reason == r_off.finish_reason

    def test_capacity_tail_gate(self, tiny):
        """Generation running to the sequence cap: within W rows of the
        table width the whole batch must fall back to plain steps (the
        verify forward writes masked rows at fill..fill+W-1), and the
        trajectory stays identical to spec-off."""
        cfg, params = tiny
        prompt = self.REP_PROMPTS[0][:8]

        def run(draft_len):
            engine = ServingEngine(cfg, params, EngineConfig(
                max_batch_size=2, max_seq_len=32,
                spec_draft_len=draft_len)).start()
            try:
                return engine.submit(prompt, max_new_tokens=24,
                                     use_eos_stop=False
                                     ).result(timeout=600).tokens
            finally:
                engine.shutdown()

        assert run(4) == run(0)

    def test_block_boundary_rollback(self, tiny):
        """Rejected drafts across block edges: with 4-token blocks and
        draft windows of 4, verify windows constantly straddle block
        boundaries and imperfect acceptance leaves rejected rows in
        freshly allocated blocks.  Rollback is fill arithmetic — the
        trajectory stays exact, no COW copies fire (no sharing here),
        and the sanitizer's block ledger stays balanced through
        drain."""
        cfg, params = tiny
        engine = ServingEngine(cfg, params, EngineConfig(
            max_batch_size=4, max_seq_len=64, max_queue_size=16,
            kv_block_size=4, spec_draft_len=3, sanitize=True)).start()
        try:
            handles = [engine.submit(p, max_new_tokens=self.MAX_NEW,
                                     use_eos_stop=False)
                       for p in self.REP_PROMPTS]
            results = [h.result(timeout=600) for h in handles]
            engine.drain(timeout=60)
            assert engine.sanitizer_report == []
        finally:
            engine.shutdown()
        for p, r in zip(self.REP_PROMPTS, results):
            assert r.tokens == _reference(cfg, params, p, self.MAX_NEW)
        snap = engine.metrics.snapshot()
        assert snap["spec_steps"] > 0
        assert snap["spec_accepted"] < snap["spec_proposed"], \
            "no rejection ever happened; the rollback path went untested"
        assert snap["cow_copies_total"] == 0

    def test_spec_metrics_shape(self, tiny):
        """The serving metrics surface for speculation: counters,
        derived acceptance rate, and the accepted-per-step histogram
        all present in snapshot() and consistent with each other."""
        _, snap = self._drive(*tiny, kv_block_size=8)
        assert snap["spec_proposed"] >= snap["spec_accepted"] >= 0
        assert snap["spec_steps"] > 0
        hist = snap["accepted_tokens_per_step"]
        assert hist["count"] > 0
        # per participating slot-step, committed = accepted + 1 bonus
        # (mid-window EOS retirement can only truncate, never add)
        total_committed = hist["mean"] * hist["count"]
        assert total_committed <= \
            snap["spec_accepted"] + hist["count"] + 1e-6
        assert hist["mean"] >= 1.0


class TestPrecisionPolicies:
    """int4 / mixed weight policies through the engine (round 9): every
    committed token must equal the one-shot ``generate_tokens``
    trajectory on the SAME quantized tree — bitwise reproducibility
    across engine modes — and the decode-step metrics must attribute
    iterations to the right precision route."""

    @pytest.mark.parametrize("policy", ["int4", "mixed"])
    def test_policy_paged_matches_one_shot(self, tiny, policy):
        import dataclasses

        from megatron_llm_tpu.ops import quant

        cfg, params = tiny
        pol = dataclasses.replace(quant.POLICIES[policy], group_size=32)
        qparams = quant.quantize_params(params, pol)
        rng = np.random.default_rng(37)
        prompts = [rng.integers(1, cfg.vocab_size, n).tolist()
                   for n in (3, 17, 9)]
        engine = ServingEngine(cfg, qparams, EngineConfig(
            max_batch_size=4, max_seq_len=64, max_queue_size=16,
            kv_block_size=8)).start()
        try:
            handles = [engine.submit(p, max_new_tokens=10,
                                     use_eos_stop=False) for p in prompts]
            results = [h.result(timeout=600) for h in handles]
        finally:
            engine.shutdown()
        for p, r in zip(prompts, results):
            assert r.finish_reason == "length"
            assert r.tokens == _reference(cfg, qparams, p, 10)

        # decode iterations attributed to the policy's precision route
        # (on CPU every step takes the composed path, so the fallback
        # breakdown is where the label must land)
        snap = engine.metrics.snapshot()
        routes = {**snap["fused_steps_by_precision"],
                  **snap["fallback_steps_by_precision"]}
        assert set(routes) == {policy}
        assert sum(snap["fallback_steps_by_precision"].values()) + \
            sum(snap["fused_steps_by_precision"].values()) > 0
