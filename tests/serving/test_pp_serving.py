"""Pipeline-parallel serving tests (CPU, 8 virtual devices, tiny model).

The pp axis is a REAL serving axis now: ``serving_param_specs`` shards
the stacked LAYER axis of params (and ``kv_pool_specs`` the pool) over
pp, and the engine microbatch-interleaves decode steps across the
stages (engine.py:_dispatch_decode).  Contracts:

- **bitwise parity** — a pp=2 engine must produce tokens bitwise equal
  to the single-chip engine across fp32/int8-kv × pipelined/classic
  decode × speculation on/off, with zero post-warmup recompiles and a
  balanced block ledger (sanitizer empty).
- **residency** — per-device param bytes at pp=2 (and at fsdp=2) are
  about half the host tree: layer (resp. non-tp dim) sharding scales
  weight residency with the mesh, the point of the layout.
- **introspection** — ``kv_snapshot()`` carries a per-stage section
  with layer ranges, device ids, and stage-local ledger views that
  agree across stages.
"""

import dataclasses

import jax
import numpy as np
import pytest

from megatron_llm_tpu.analysis.sanitizers import no_recompiles
from megatron_llm_tpu.config import ParallelConfig, tiny_config
from megatron_llm_tpu.models import model as model_lib
from megatron_llm_tpu.serving import (
    EngineConfig,
    ServingEngine,
    build_sharded_engine,
)


@pytest.fixture(scope="module")
def tiny():
    cfg = tiny_config(num_layers=2, vocab_size=64,
                      make_vocab_size_divisible_by=8)
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


def _prompts(cfg, n, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, cfg.vocab_size,
                         int(rng.integers(4, 12))).tolist()
            for _ in range(n)]


def _run(engine, specs, timeout=120):
    handles = engine.submit_many(specs)
    return [list(h.result(timeout).tokens) for h in handles]


def _reference_tokens(cfg, params, specs, **cfg_overrides):
    kw = dict(max_batch_size=2, max_seq_len=64, max_queue_size=32,
              prefill_bucket=16)
    kw.update(cfg_overrides)
    engine = ServingEngine(cfg, params, EngineConfig(**kw)).start()
    try:
        return _run(engine, specs)
    finally:
        engine.shutdown()


@pytest.mark.parametrize("kv_quant", ["none", "int8"])
@pytest.mark.parametrize("pipeline", [True, False],
                         ids=["pipelined", "classic"])
def test_pp_engine_bitwise_matches_single_chip(tiny, devices, kv_quant,
                                               pipeline):
    cfg, params = tiny
    if kv_quant != "none":
        cfg = dataclasses.replace(cfg, kv_cache_quant=kv_quant).validate()
    specs = [dict(prompt=p, max_new_tokens=10, seed=i, use_eos_stop=False)
             for i, p in enumerate(_prompts(cfg, 3))]
    ref = _reference_tokens(cfg, params, specs, pipeline_decode=pipeline)

    engine = build_sharded_engine(
        cfg, params,
        EngineConfig(max_batch_size=2, max_seq_len=64, max_queue_size=32,
                     prefill_bucket=16, pipeline_decode=pipeline,
                     sanitize=True),
        parallel=ParallelConfig(pipeline_parallel=2),
        devices=devices[:2])
    assert engine.mesh is not None
    try:
        engine.start()
        # the microbatch interleave must engage: max_batch_size 2 splits
        # into pp=2 groups of one slot each
        assert engine._decode_groups == 2
        _run(engine, specs)  # warmup: all shapes compile here
        with no_recompiles():
            got = _run(engine, specs)
    finally:
        engine.shutdown()
    assert got == ref
    # balanced ledgers on every stage: the ledger is host-global, so one
    # empty leak report covers all stages
    assert engine.sanitizer_report == []


@pytest.mark.parametrize("spec_len", [0, 3], ids=["nospec", "spec"])
def test_pp_engine_speculative_bitwise(tiny, devices, spec_len):
    cfg, params = tiny
    specs = [dict(prompt=p, max_new_tokens=12, seed=i, use_eos_stop=False)
             for i, p in enumerate(_prompts(cfg, 3, seed=7))]
    ref = _reference_tokens(cfg, params, specs, spec_draft_len=spec_len)

    engine = build_sharded_engine(
        cfg, params,
        EngineConfig(max_batch_size=2, max_seq_len=64, max_queue_size=32,
                     prefill_bucket=16, spec_draft_len=spec_len,
                     sanitize=True),
        parallel=ParallelConfig(pipeline_parallel=2),
        devices=devices[:2])
    try:
        engine.start()
        _run(engine, specs)
        with no_recompiles():
            got = _run(engine, specs)
    finally:
        engine.shutdown()
    assert got == ref
    assert engine.sanitizer_report == []


def test_pp_params_are_actually_layer_sharded(tiny, devices):
    cfg, params = tiny
    engine = build_sharded_engine(
        cfg, params, EngineConfig(max_batch_size=2, max_seq_len=64),
        parallel=ParallelConfig(pipeline_parallel=2), devices=devices[:2])
    total = sum(np.asarray(l).nbytes for l in jax.tree.leaves(params))
    per_dev = sum(l.addressable_shards[0].data.nbytes
                  for l in jax.tree.leaves(engine.params))
    # every stacked [L, ...] layer leaf splits 2-way over pp; only the
    # embedding/final-norm (and biases) stay replicated
    assert per_dev < 0.75 * total, (per_dev, total)
    # and the paged pool itself is layer-sharded once started
    engine.start()
    try:
        pool = engine.slots.pool
        k = pool.k_pool["q"] if isinstance(pool.k_pool, dict) else pool.k_pool
        per_dev_kv = k.addressable_shards[0].data.nbytes
        assert per_dev_kv * 2 == k.nbytes, (per_dev_kv, k.nbytes)
    finally:
        engine.shutdown()


def test_fsdp_params_residency(tiny, devices):
    cfg, params = tiny
    engine = build_sharded_engine(
        cfg, params, EngineConfig(max_batch_size=2, max_seq_len=64),
        parallel=ParallelConfig(fsdp=2), devices=devices[:2])
    total = sum(np.asarray(l).nbytes for l in jax.tree.leaves(params))
    per_dev = sum(l.addressable_shards[0].data.nbytes
                  for l in jax.tree.leaves(engine.params))
    # fsdp splits EVERY projection along its non-tp dim AND the vocab
    # embedding along ('tp','fsdp'), so residency lands very near 1/2
    assert per_dev < 0.75 * total, (per_dev, total)


def test_pp_kv_snapshot_stages(tiny, devices):
    cfg, params = tiny
    engine = build_sharded_engine(
        cfg, params,
        EngineConfig(max_batch_size=2, max_seq_len=64, max_queue_size=32,
                     prefill_bucket=16),
        parallel=ParallelConfig(pipeline_parallel=2), devices=devices[:2])
    try:
        engine.start()
        specs = [dict(prompt=p, max_new_tokens=6, seed=i,
                      use_eos_stop=False)
                 for i, p in enumerate(_prompts(cfg, 2))]
        _run(engine, specs)
        snap = engine.kv_snapshot()
        stages = snap["stages"]
        assert [s["stage"] for s in stages] == [0, 1]
        # contiguous layer slabs covering the whole stack
        assert stages[0]["layers"] == [0, cfg.num_layers // 2]
        assert stages[1]["layers"] == [cfg.num_layers // 2, cfg.num_layers]
        # disjoint one-device stages on this submesh
        assert stages[0]["devices"] != stages[1]["devices"]
        # balanced ledgers: identical stage-local views everywhere
        for key in ("blocks_free", "blocks_used", "fragmentation"):
            assert stages[0][key] == stages[1][key]
        # the renderer consumes the section without error
        from megatron_llm_tpu.tools.dump_kv_pool import summarize
        text = summarize(snap)
        assert "pipeline stages: 2" in text
        assert "stage 1: layers" in text
    finally:
        engine.shutdown()


def test_pp_geometry_guard_names_the_axis(tiny, devices):
    """The old fused 'heads % pp·tp' guard is gone: a layer count that
    doesn't divide pp must fail on the LAYER message, not a head one."""
    cfg, params = tiny  # num_layers=2
    bad = dataclasses.replace(cfg, num_layers=3,
                              max_position_embeddings=128).validate()
    bad_params = model_lib.init_params(jax.random.key(0), bad)
    with pytest.raises(AssertionError, match="layer stack over pp"):
        build_sharded_engine(
            bad, bad_params, EngineConfig(max_batch_size=2, max_seq_len=64),
            parallel=ParallelConfig(pipeline_parallel=2),
            devices=devices[:2])
