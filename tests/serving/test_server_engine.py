"""Engine-backed REST service contract: prompt batches beyond the slot
count are queued and served (no more hard 400), queue saturation maps to
503 + Retry-After, and the sequence-budget 400 survives."""

import json
import urllib.error
import urllib.request

import jax
import pytest

from megatron_llm_tpu.config import tiny_config
from megatron_llm_tpu.generation.server import (
    GenerationService,
    MegatronServer,
)
from megatron_llm_tpu.models import model as model_lib
from megatron_llm_tpu.tokenizer.tokenizer import NullTokenizer


@pytest.fixture(scope="module")
def model():
    cfg = tiny_config(num_layers=1, vocab_size=256,
                      make_vocab_size_divisible_by=8)
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


def test_more_prompts_than_slots_is_served(model):
    """Six prompts through two KV slots: the old server rejected this with
    400; the engine queues and serves all of them."""
    cfg, params = model
    svc = GenerationService(cfg, params,
                            NullTokenizer(vocab_size=cfg.vocab_size),
                            max_batch_size=2, queue_size=16)
    try:
        prompts = [f"{10 + i} {20 + i} {30 + i}" for i in range(6)]
        status, out = svc.handle({"prompts": prompts,
                                  "tokens_to_generate": 3,
                                  "no_early_termination": True})
        assert status == 200
        assert len(out["text"]) == 6
        # legacy ragged-batch contract: budget = max prompt len + ttg, so
        # these equal-length prompts each return 3 + 3 tokens
        assert all(len(t.split()) == 6 for t in out["text"])
        snap = svc.engine.metrics.snapshot()
        assert snap["completed"] == 6
        assert snap["max_decode_batch"] <= 2  # only two slots exist
    finally:
        svc.close()


def test_engine_and_legacy_path_agree(model):
    """A 4-slot (batched) and a 1-slot (serialized) service must return
    identical text for the same greedy and seeded-sampling requests —
    batch composition must never change results."""
    cfg, params = model
    tok = NullTokenizer(vocab_size=cfg.vocab_size)
    a = GenerationService(cfg, params, tok, max_batch_size=4)
    b = GenerationService(cfg, params, tok, max_batch_size=1)  # serialized
    try:
        for body in ({"prompts": ["7 8 9 10", "11 12 13"],
                      "tokens_to_generate": 6,
                      "no_early_termination": True},
                     {"prompts": ["7 8 9 10"], "tokens_to_generate": 4,
                      "top_k": 4, "random_seed": 3}):
            s1, o1 = a.handle(dict(body))
            s2, o2 = b.handle(dict(body))
            assert s1 == s2 == 200
            assert o1["text"] == o2["text"]
    finally:
        a.close()
        b.close()


def test_queue_full_maps_to_503(model):
    cfg, params = model
    svc = GenerationService(cfg, params,
                            NullTokenizer(vocab_size=cfg.vocab_size),
                            max_batch_size=1, queue_size=2,
                            retry_after_s=7.0)
    try:
        engine = svc.engine
        engine.pause()  # deterministic pressure: nothing drains
        engine.submit([5], max_new_tokens=2)  # fill the queue directly
        engine.submit([6], max_new_tokens=2)
        status, payload = svc.handle({"prompts": ["7 8"],
                                      "tokens_to_generate": 2})
        assert status == 503
        assert payload["retry_after"] == 7
        assert "queue" in payload["message"]
    finally:
        svc.close()


def test_oversized_batch_maps_to_503(model):
    """A batch that can NEVER fit the bounded queue is backpressure (503,
    try smaller/again later), not a validation error."""
    cfg, params = model
    svc = GenerationService(cfg, params,
                            NullTokenizer(vocab_size=cfg.vocab_size),
                            max_batch_size=1, queue_size=2)
    try:
        status, payload = svc.handle(
            {"prompts": ["1", "2", "3"], "tokens_to_generate": 2})
        assert status == 503
        assert "retry_after" in payload
    finally:
        svc.close()


def test_sequence_budget_is_still_400(model):
    cfg, params = model
    svc = GenerationService(cfg, params,
                            NullTokenizer(vocab_size=cfg.vocab_size),
                            engine_max_seq_len=16)
    try:
        status, msg = svc.handle({"prompts": ["1 2 3 4 5 6 7 8"],
                                  "tokens_to_generate": 12})  # 8 + 12 > 16
        assert status == 400
        assert "sequence budget" in msg
        # within budget works
        status, out = svc.handle({"prompts": ["1 2 3 4"],
                                  "tokens_to_generate": 4})
        assert status == 200 and len(out["text"]) == 1
    finally:
        svc.close()


def test_http_503_carries_retry_after_header(model):
    cfg, params = model
    server = MegatronServer(cfg, params,
                            NullTokenizer(vocab_size=cfg.vocab_size),
                            max_batch_size=1, queue_size=1,
                            retry_after_s=9.0)
    server.run("127.0.0.1", 0, block=False)
    try:
        engine = server.service.engine
        engine.pause()
        engine.submit([5], max_new_tokens=2)  # saturate the queue
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/api",
            data=json.dumps({"prompts": ["7 8"],
                             "tokens_to_generate": 2}).encode(),
            method="PUT", headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=60)
        assert ei.value.code == 503
        assert ei.value.headers["Retry-After"] == "9"
        body = json.loads(ei.value.read())
        assert body["retry_after"] == 9
    finally:
        server.shutdown()


def test_http_metrics_endpoint(model):
    """GET /metrics returns the live serving snapshot as JSON, including
    the device/host step breakdown the fast path exposes."""
    cfg, params = model
    server = MegatronServer(cfg, params,
                            NullTokenizer(vocab_size=cfg.vocab_size),
                            max_batch_size=2)
    server.run("127.0.0.1", 0, block=False)
    try:
        url = f"http://127.0.0.1:{server.port}/metrics"
        # scraping a server whose engine was never created must not
        # instantiate the slot cache — and still answer
        with urllib.request.urlopen(url, timeout=60) as resp:
            cold = json.loads(resp.read())
        assert cold["completed"] == 0
        assert server.service._engine is None

        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/api",
            data=json.dumps({"prompts": ["5 9 3"], "tokens_to_generate": 4,
                             "no_early_termination": True}).encode(),
            method="PUT", headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=600) as resp:
            assert resp.status == 200
        with urllib.request.urlopen(url, timeout=60) as resp:
            snap = json.loads(resp.read())

        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/other", timeout=60)
        assert ei.value.code == 404
    finally:
        server.shutdown()
    assert snap["completed"] == 1
    assert snap["decode_iterations"] > 0
    assert snap["device_step_time"]["count"] > 0
    assert "device_idle_frac" in snap and "sched_host_time" in snap


def test_kv_endpoint_and_dump_tool(model):
    """GET /kv: ``pool: null`` before the lazy engine exists, live pool
    stats + per-slot tables after traffic; tools/dump_kv_pool.py renders
    the same snapshot end-to-end against the HTTP endpoint."""
    from megatron_llm_tpu.tools import dump_kv_pool

    cfg, params = model
    server = MegatronServer(cfg, params,
                            NullTokenizer(vocab_size=cfg.vocab_size),
                            max_batch_size=2, kv_block_size=8)
    server.run("127.0.0.1", 0, block=False)
    try:
        kv_url = f"http://127.0.0.1:{server.port}/kv"
        with urllib.request.urlopen(kv_url, timeout=60) as resp:
            assert resp.status == 200
            pre = json.loads(resp.read())
        assert pre == {"pool": None, "slots": {}}  # engine not started yet

        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/api",
            data=json.dumps({"prompts": ["5 9 3 7"],
                             "tokens_to_generate": 4,
                             "no_early_termination": True}).encode(),
            method="PUT", headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=600) as resp:
            assert resp.status == 200

        with urllib.request.urlopen(kv_url, timeout=60) as resp:
            snap = json.loads(resp.read())
        pool = snap["pool"]
        assert pool["block_size"] == 8
        assert pool["blocks_used"] + pool["blocks_free"] \
            + 1 == pool["n_blocks"]  # trash block is neither used nor free
        assert pool["cow_copies"] == 0
        assert snap["table_blocks"] >= 1
        assert isinstance(snap["slots"], dict)  # request retired -> empty

        assert dump_kv_pool.main(["--url", kv_url.removesuffix("/kv")]) == 0
    finally:
        server.shutdown()
