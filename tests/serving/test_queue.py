"""Bounded admission queue: FIFO order, all-or-nothing batch reservation,
backpressure (QueueFull + retry hint), cancellation removal, consumer
wakeup."""

import threading

import pytest

from megatron_llm_tpu.serving import QueueFull, RequestQueue


def test_fifo_order():
    q = RequestQueue(max_size=4)
    a, b, c = object(), object(), object()
    q.put(a)
    q.put_many([b, c])
    assert len(q) == 3 and q.free_space == 1
    assert q.pop() is a and q.pop() is b and q.pop() is c
    assert q.pop() is None
    assert q.free_space == 4


def test_bounded_put_raises_queue_full():
    q = RequestQueue(max_size=2, retry_after_s=5.0)
    q.put(object())
    q.put(object())
    with pytest.raises(QueueFull) as ei:
        q.put(object())
    assert ei.value.retry_after_s == 5.0
    assert len(q) == 2


def test_put_many_all_or_nothing():
    q = RequestQueue(max_size=3)
    q.put_many([object(), object()])
    with pytest.raises(QueueFull):
        q.put_many([object(), object()])  # only 1 free: admit neither
    assert len(q) == 2
    q.pop()
    q.put_many([object(), object()])  # 2 free now
    assert len(q) == 3


def test_put_many_larger_than_capacity():
    q = RequestQueue(max_size=3)
    with pytest.raises(QueueFull, match="exceeds the queue capacity"):
        q.put_many([object()] * 4)  # can never fit, even empty
    assert len(q) == 0


def test_remove_queued_request():
    q = RequestQueue(max_size=4)
    a, b = object(), object()
    q.put_many([a, b])
    assert q.remove(a) is True
    assert q.remove(a) is False  # already gone
    assert q.pop() is b


def test_wait_for_work():
    q = RequestQueue(max_size=4)
    assert q.wait_for_work(timeout=0.01) is False
    item = object()
    t = threading.Timer(0.05, q.put, args=(item,))
    t.start()
    try:
        assert q.wait_for_work(timeout=30) is True
    finally:
        t.cancel()
    assert q.pop() is item
