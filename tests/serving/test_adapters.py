"""Multi-tenant LoRA serving (serving/adapters/ + engine plumbing).

The load-bearing invariant: every token of a mixed-adapter decode batch
is bitwise-equal to the same request run ALONE on the same engine —
across fp32/int8/int4 weights, paged/fixed-stride KV, and speculative
decoding on/off — because slot-masked arena columns contribute exact
±0.0 to other rows.  Plus the cache mechanics (LRU + ref pinning under
an eviction storm), live weight swap mid-traffic, and the
zero-recompile guarantee as adapters rotate through the arena.
"""

import dataclasses
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from megatron_llm_tpu.analysis.sanitizers import no_recompiles
from megatron_llm_tpu.config import tiny_config
from megatron_llm_tpu.models import model as model_lib
from megatron_llm_tpu.ops.lora import init_lora_adapter
from megatron_llm_tpu.serving import (
    AdapterRegistry,
    EngineConfig,
    ServingEngine,
)

PROMPT = [3, 5, 7, 11, 13]
# repetitive so the prompt-lookup drafter engages in the spec variants
REP_PROMPT = [5, 9, 3, 5, 9, 3, 5, 9, 3, 5, 9]


@pytest.fixture(scope="module")
def tiny():
    cfg = tiny_config(num_layers=2, vocab_size=64,
                      make_vocab_size_divisible_by=8)
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


def _adapter(cfg, seed, rank=4, **kw):
    """Adapter with non-trivial B so its delta actually moves logits."""
    ad = init_lora_adapter(cfg, jax.random.key(seed), rank, alpha=32.0,
                           **kw)
    return dataclasses.replace(ad, factors={
        t: {"a": f["a"],
            "b": jax.random.normal(jax.random.key(seed + 500),
                                   f["b"].shape, f["b"].dtype) * 0.05}
        for t, f in ad.factors.items()})


def _registry(cfg, n_adapters=3, n_slots=2, rank=4):
    reg = AdapterRegistry(cfg, n_slots=n_slots, rank=rank)
    for i in range(n_adapters):
        reg.register(f"t{i}", _adapter(cfg, 100 + i, rank))
    return reg


# ---------------------------------------------------------------------------
# registry unit behavior
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_register_validates(self, tiny):
        cfg, _ = tiny
        reg = AdapterRegistry(cfg, n_slots=2, rank=4)
        with pytest.raises(ValueError, match="rank"):
            reg.register("r8", _adapter(cfg, 1, rank=8))
        reg.register("a", _adapter(cfg, 2))
        assert reg.known("a") and not reg.known("b")
        with pytest.raises(KeyError):
            reg.acquire("never-registered")

    def test_lru_eviction_and_ref_pinning(self, tiny):
        cfg, _ = tiny
        reg = _registry(cfg, n_adapters=4, n_slots=2)
        s0 = reg.acquire("t0")
        s1 = reg.acquire("t1")
        assert {s0, s1} == {0, 1}
        # arena full, both pinned: no victim available
        assert reg.acquire("t2") is None
        reg.release("t0")                       # t0 unpinned -> evictable
        s2 = reg.acquire("t2")
        assert s2 == s0 and not reg.is_resident("t0")
        assert reg.is_resident("t1")            # pinned survivor
        # re-acquiring the resident is a hit, not an install
        assert reg.acquire("t1") == s1
        reg.release("t1")
        reg.release("t1")
        reg.release("t2")
        assert all(reg.pins(a) == 0 for a in reg.resident())

    def test_resident_adapter_cannot_be_replaced(self, tiny):
        cfg, _ = tiny
        reg = _registry(cfg, n_adapters=2, n_slots=1)
        reg.acquire("t0")
        with pytest.raises(ValueError, match="resident"):
            reg.register("t0", _adapter(cfg, 9))
        reg.release("t0")
        # parked is still resident (its arena columns are live)
        with pytest.raises(ValueError, match="resident"):
            reg.register("t0", _adapter(cfg, 9))
        reg.acquire("t1")                       # evicts the parked t0
        reg.register("t0", _adapter(cfg, 9))    # evicted: replace is fine
        reg.release("t1")

    def test_clone_shares_store_not_residency(self, tiny):
        cfg, _ = tiny
        reg = _registry(cfg, n_adapters=2, n_slots=2)
        reg.acquire("t0")
        twin = reg.clone()
        assert twin.known("t0") and twin.known("t1")
        assert not twin.is_resident("t0")       # fresh arena, no pins
        assert reg.is_resident("t0")            # original untouched
        twin.register("t9", _adapter(cfg, 77))
        assert not reg.known("t9")              # stores diverge after clone
        reg.release("t0")


# ---------------------------------------------------------------------------
# the bitwise acceptance matrix
# ---------------------------------------------------------------------------


class TestMixedBatchBitwise:
    """Mixed-adapter batch tokens == per-request-alone tokens, bitwise,
    on the SAME engine (fixed batch geometry): fp32/int8/int4 weights x
    paged/fixed-stride KV x speculative decoding on/off."""

    @pytest.fixture(scope="class")
    def quantized(self, tiny):
        from megatron_llm_tpu.ops.quant import (quantize_params,
                                                resolve_policy)

        cfg, params = tiny
        return {
            "fp32": params,
            "int8": quantize_params(params, resolve_policy("int8")),
            "int4": quantize_params(params, resolve_policy("int4")),
        }

    def _drive(self, cfg, params, spec, **overrides):
        kw = dict(max_batch_size=4, max_seq_len=64, max_queue_size=16,
                  adapter_cache_slots=2, prefix_cache_blocks=0)
        if spec:
            kw["spec_draft_len"] = 3
        kw.update(overrides)
        reg = _registry(cfg, n_adapters=2, n_slots=2)
        prompt = REP_PROMPT if spec else PROMPT
        max_new = 16 if spec else 8
        specs = [dict(adapter_id="t0"), dict(), dict(adapter_id="t1"),
                 dict(adapter_id="t0")]
        engine = ServingEngine(cfg, params, EngineConfig(**kw),
                               adapters=reg).start()
        try:
            alone = [engine.submit(prompt, max_new, use_eos_stop=False,
                                   **s).result(600).tokens
                     for s in specs]
            handles = [engine.submit(prompt, max_new, use_eos_stop=False,
                                     **s) for s in specs]
            mixed = [h.result(600).tokens for h in handles]
            snap = engine.metrics.snapshot()
        finally:
            engine.shutdown()
        assert mixed == alone                    # bitwise, per request
        assert alone[0] != alone[1]              # t0 really diverges
        assert alone[2] != alone[1]              # t1 really diverges
        assert alone[2] != alone[0]              # ...differently
        assert snap["max_decode_batch"] >= 2     # batch actually mixed
        if spec:
            assert snap["spec_steps"] > 0, "drafter never engaged"
        assert engine.sanitizer_report == []
        return snap

    @pytest.mark.parametrize("spec", [False, True], ids=["plain", "spec"])
    @pytest.mark.parametrize("layout", ["paged", "dense"])
    @pytest.mark.parametrize("precision", ["fp32", "int8", "int4"])
    def test_matrix(self, tiny, quantized, precision, layout, spec):
        cfg, _ = tiny
        block = 8 if layout == "paged" else 64
        self._drive(cfg, quantized[precision], spec, kv_block_size=block)


# ---------------------------------------------------------------------------
# cache churn, parking, and the ledger
# ---------------------------------------------------------------------------


def test_eviction_storm_ref_pinning(tiny):
    """8 concurrent requests over 4 adapters through a 2-slot arena:
    admission parks when every slot is pinned, evictions rotate parked
    adapters in as pins drop, and every stream still equals its alone
    run bitwise.  Pins return to zero and the block ledger balances."""
    cfg, params = tiny
    reg = _registry(cfg, n_adapters=4, n_slots=2)
    ecfg = EngineConfig(max_batch_size=2, max_seq_len=64,
                        max_queue_size=32, adapter_cache_slots=2,
                        prefix_cache_blocks=0)
    engine = ServingEngine(cfg, params, ecfg, adapters=reg).start()
    try:
        # pairs: the second request of each pair finds its adapter
        # pinned by the first (a hit); across pairs the arena thrashes
        ids = [f"t{(i // 2) % 4}" for i in range(8)]
        alone = {aid: engine.submit(PROMPT, 8, use_eos_stop=False,
                                    adapter_id=aid).result(600).tokens
                 for aid in sorted(set(ids))}
        handles = [engine.submit(PROMPT, 8, use_eos_stop=False,
                                 adapter_id=aid) for aid in ids]
        results = [h.result(600).tokens for h in handles]
        snap = engine.metrics.snapshot()
    finally:
        engine.shutdown()
    for aid, toks in zip(ids, results):
        assert toks == alone[aid]
    assert snap["adapter_evictions"] > 0        # the storm really churned
    assert snap["adapter_hits"] > 0
    assert all(reg.pins(a) == 0 for a in reg.resident())
    assert engine.sanitizer_report == []


def test_unknown_adapter_rejected_at_submit(tiny):
    cfg, params = tiny
    reg = _registry(cfg, n_adapters=1, n_slots=2)
    ecfg = EngineConfig(max_batch_size=2, max_seq_len=64,
                        adapter_cache_slots=2)
    engine = ServingEngine(cfg, params, ecfg, adapters=reg).start()
    try:
        with pytest.raises(ValueError, match="unknown adapter"):
            engine.submit(PROMPT, 4, adapter_id="never-registered")
        # and with no registry at all, naming any adapter is an error
    finally:
        engine.shutdown()
    bare = ServingEngine(cfg, params, EngineConfig(
        max_batch_size=2, max_seq_len=64)).start()
    try:
        with pytest.raises(ValueError, match="adapter"):
            bare.submit(PROMPT, 4, adapter_id="t0")
    finally:
        bare.shutdown()


def test_no_recompiles_as_adapters_rotate(tiny):
    """After warmup, adapter churn — cache hits, misses with installs,
    evictions, base-only rows — must not compile anything new: the slot
    mask is built inside the jit from a traced operand and the install
    executable is slot-traced."""
    cfg, params = tiny
    reg = _registry(cfg, n_adapters=3, n_slots=2)
    ecfg = EngineConfig(max_batch_size=4, max_seq_len=64,
                        max_queue_size=16, adapter_cache_slots=2,
                        prefix_cache_blocks=0)
    engine = ServingEngine(cfg, params, ecfg, adapters=reg).start()
    try:
        # warmup: prefill + decode + install, with and without adapter
        engine.submit(PROMPT, 4, use_eos_stop=False,
                      adapter_id="t0").result(600)
        engine.submit(PROMPT, 4, use_eos_stop=False).result(600)
        with no_recompiles():
            handles = [
                engine.submit(PROMPT, 6, use_eos_stop=False,
                              adapter_id=aid)
                for aid in ("t0", "t1", "t2", None)]
            for h in handles:
                h.result(600)
    finally:
        engine.shutdown()


# ---------------------------------------------------------------------------
# live weight swap
# ---------------------------------------------------------------------------


def test_swap_params_mid_traffic_loses_no_tokens(tiny):
    """swap_params fences at an iteration boundary: an in-flight stream
    keeps decoding across the swap, every token is delivered exactly
    once, and the old tree comes back to the caller."""
    cfg, params = tiny
    reg = _registry(cfg, n_adapters=1, n_slots=2)
    ecfg = EngineConfig(max_batch_size=2, max_seq_len=96,
                        adapter_cache_slots=2, prefix_cache_blocks=0)
    engine = ServingEngine(cfg, params, ecfg, adapters=reg).start()
    params2 = model_lib.init_params(jax.random.key(99), cfg)
    got = []
    try:
        h = engine.submit(PROMPT, 48, use_eos_stop=False,
                          adapter_id="t0", on_token=got.append)
        time.sleep(0.05)
        old = engine.swap_params(params2)
        r = h.result(600)
    finally:
        engine.shutdown()
    assert old is params
    gen = r.tokens[len(PROMPT):]
    assert len(gen) == 48                      # nothing lost
    assert got == gen                          # nothing duplicated
    assert engine.metrics.snapshot()["param_swaps"] == 1
    assert engine.sanitizer_report == []


def test_swap_params_rejects_mismatched_tree(tiny):
    cfg, params = tiny
    engine = ServingEngine(cfg, params, EngineConfig(
        max_batch_size=2, max_seq_len=64)).start()
    bad_cfg = tiny_config(num_layers=1, vocab_size=64,
                          make_vocab_size_divisible_by=8)
    try:
        with pytest.raises(ValueError, match="structure|shape"):
            engine.swap_params(model_lib.init_params(jax.random.key(1),
                                                     bad_cfg))
        # the engine still serves after the refused swap
        r = engine.submit(PROMPT, 4, use_eos_stop=False).result(600)
        assert len(r.tokens) == len(PROMPT) + 4
    finally:
        engine.shutdown()
