"""Cluster self-healing tests (docs/robustness.md, "Cluster
self-healing"): the supervised replica lifecycle under injected faults.

- **rebuild after crash** — a chaos scheduler-step crash kills a replica
  raw; the supervisor rebuilds it on its original submesh, re-warms it
  off-rotation, and rejoins it at a bumped generation, while the
  in-flight requests fail over with bitwise client streams.
- **poison quarantine** — a request whose admission deterministically
  crashes its host engine is finished with ``finish_reason=
  "quarantined"`` after its second crash instead of being resubmitted to
  kill a third replica; both crashed replicas rebuild and subsequent
  traffic runs at full capacity with zero post-warmup recompiles.
- **hung-step watchdog** — a wedged device dispatch (thread alive,
  iteration heartbeat stale) is detected, killed, and rebuilt.
- **shipment I/O faults** — chaos ``fail_io`` on the export/import
  ``device_put`` paths: the request keeps decoding at home (export) or
  reinstalls at the source (import), ledgers balanced on both submeshes
  and client streams bitwise.
- **router backpressure** — an all-draining cluster surfaces as HTTP
  503 + Retry-After with a ``router_queue_full`` EVENT_LOG line.
- **deadline-aware failover** — a request whose wall-clock budget
  expired before failover finishes with ``"timeout"`` instead of
  burning a slot on a dead-on-arrival resubmit; a live budget is passed
  through as the *remaining* time, never a fresh one.
- **compound-fault soak** — the randomized kill/hang/ship-fault storm
  over ≥ 64 mixed requests (serving/bench.py:run_chaos_soak_bench):
  exactly-once delivery, balanced ledgers on every incarnation, cluster
  back at full strength.
"""

import time

import jax
import numpy as np
import pytest

from megatron_llm_tpu.analysis.sanitizers import no_recompiles
from megatron_llm_tpu.config import tiny_config
from megatron_llm_tpu.models import model as model_lib
from megatron_llm_tpu.obs.logging import EVENT_LOG
from megatron_llm_tpu.resilience import chaos
from megatron_llm_tpu.serving import (
    EngineConfig,
    ReplicaSupervisor,
    RouterConfig,
    ServingEngine,
    SupervisorConfig,
    build_cluster,
    build_disagg_cluster,
)

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def clean_chaos():
    # tests/serving has no chaos bootstrap (unlike tests/resilience) —
    # the controller is process-global, so disarm around every test
    chaos().reset()
    EVENT_LOG.clear()
    yield
    chaos().reset()


@pytest.fixture(scope="module")
def tiny():
    cfg = tiny_config(num_layers=2, vocab_size=64,
                      make_vocab_size_divisible_by=8)
    params = model_lib.init_params(jax.random.key(0), cfg)
    return cfg, params


def _prompts(cfg, n, seed=0, lo=4, hi=12):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, cfg.vocab_size,
                         int(rng.integers(lo, hi))).tolist()
            for _ in range(n)]


def _run(engine_or_router, specs, timeout=300):
    handles = engine_or_router.submit_many(specs)
    return [h.result(timeout) for h in handles]


def _reference_tokens(cfg, params, specs, **cfg_overrides):
    """Uninterrupted single-chip engine run — the parity baseline."""
    kw = dict(max_batch_size=2, max_seq_len=64, max_queue_size=32)
    kw.update(cfg_overrides)
    engine = ServingEngine(cfg, params, EngineConfig(**kw)).start()
    try:
        return [list(r.tokens) for r in _run(engine, specs)]
    finally:
        engine.shutdown()


def _heal(router, timeout=300.0) -> bool:
    """Wait until every replica is alive again (supervisor rebuilt)."""
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < timeout:
        if all(r.alive() and not r.dead for r in router.replicas):
            return True
        time.sleep(0.02)
    return False


def _ec(**kw):
    base = dict(max_batch_size=1, max_seq_len=64, max_queue_size=32,
                prefill_bucket=16, sanitize=True)
    base.update(kw)
    return EngineConfig(**base)


def _supervise(router, **kw):
    kw.setdefault("interval_s", 0.02)
    kw.setdefault("warm_specs", [dict(prompt=[1, 2, 3, 4],
                                      max_new_tokens=2,
                                      use_eos_stop=False)] * 3)
    return ReplicaSupervisor(router, SupervisorConfig(**kw)).start()


# ---------------------------------------------------------------------------
# tentpole: crash rebuild, watchdog, poison quarantine
# ---------------------------------------------------------------------------

def test_supervisor_rebuilds_crashed_replica(tiny):
    cfg, params = tiny
    specs = [dict(prompt=p, max_new_tokens=10, seed=i, use_eos_stop=False)
             for i, p in enumerate(_prompts(cfg, 4, seed=1))]
    ref = _reference_tokens(cfg, params, specs)
    router = build_cluster(
        cfg, params, _ec(), replicas=2,
        router_config=RouterConfig(probe_interval_s=0.02)).start()
    sup = _supervise(router, hang_timeout_s=0)
    try:
        handles = router.submit_many(specs)
        time.sleep(0.1)  # let both schedulers take work
        # raw scheduler-step crash: no cleanup, no request failed by the
        # engine — probe-detected, exactly like a real kill
        chaos().crash_at("serve-step")
        results = [h.result(300) for h in handles]

        # zero lost accepted tokens: bitwise the uninterrupted run
        assert [list(r.tokens) for r in results] == ref
        assert ("crash", "serve-step") in chaos().events

        # capacity restored: the dead replica rebuilt on its submesh and
        # rejoined at a bumped generation
        assert _heal(router)
        assert sup.rebuilt_total >= 1
        assert sum(r.generation for r in router.replicas) \
            == sup.rebuilt_total
        assert EVENT_LOG.recent(event="replica_rebuilding")
        rejoined = EVENT_LOG.recent(event="replica_rejoined")
        assert rejoined and rejoined[-1]["generation"] >= 1
        assert any(ev["name"] == "rebuild"
                   for ev in router.trace.chrome_trace()["traceEvents"])

        # the rebuilt cluster serves a fresh wave at full strength
        again = _run(router, specs)
        assert [list(r.tokens) for r in again] == ref
        snap = router.snapshot()
        assert snap["router"]["usable"] == 2
        assert snap["router"]["replicas_rebuilt_total"] == \
            sup.rebuilt_total
    finally:
        router.shutdown()
    # ledgers balanced on every incarnation, dead ones included
    for r in router.replicas:
        assert r.engine.sanitizer_report == []
    for reports in sup.incarnation_reports.values():
        for rep in reports:
            assert rep == []


def test_watchdog_kills_wedged_replica(tiny):
    cfg, params = tiny
    specs = [dict(prompt=p, max_new_tokens=10, seed=i, use_eos_stop=False)
             for i, p in enumerate(_prompts(cfg, 4, seed=2))]
    ref = _reference_tokens(cfg, params, specs)
    router = build_cluster(
        cfg, params, _ec(), replicas=2,
        router_config=RouterConfig(probe_interval_s=0.02)).start()
    sup = _supervise(router, hang_timeout_s=0.4)
    try:
        handles = router.submit_many(specs)
        time.sleep(0.1)
        # wedge one dispatch: thread stays alive, the iteration
        # heartbeat goes stale — only the watchdog can see this
        chaos().hang_at("serve-dispatch", seconds=2.0)
        results = [h.result(300) for h in handles]
        assert [list(r.tokens) for r in results] == ref
        assert ("hang", "serve-dispatch") in chaos().events
        assert _heal(router)
        assert sup.watchdog_trips_total >= 1
        assert sup.rebuilt_total >= 1
        assert EVENT_LOG.recent(event="watchdog_trip")
        snap = router.snapshot()
        assert snap["router"]["usable"] == 2
        assert snap["router"]["watchdog_trips_total"] >= 1
    finally:
        router.shutdown()


def test_poison_request_quarantined_then_full_capacity(tiny):
    cfg, params = tiny
    wave = [dict(prompt=p, max_new_tokens=8, seed=i, use_eos_stop=False)
            for i, p in enumerate(_prompts(cfg, 6, seed=3, lo=8, hi=17))]
    ref = _reference_tokens(cfg, params, wave)
    warm = [dict(prompt=list(wave[0]["prompt"]), max_new_tokens=4,
                 use_eos_stop=False)] * 3
    router = build_cluster(
        cfg, params, _ec(max_batch_size=2), replicas=3,
        router_config=RouterConfig(probe_interval_s=0.02, max_resubmits=4,
                                   quarantine_after=2)).start()
    sup = _supervise(router, hang_timeout_s=0, warm_specs=warm)
    try:
        # warm every original replica with workload-shaped traffic
        for _ in range(2):
            _run(router, wave)

        # the poison request: crashes whichever engine ADMITS it, keyed
        # to its resolved seed so the crash follows it across failover
        poison_seed = 1234
        chaos().crash_at(f"serve-admit:{poison_seed}", times=2)
        [h] = router.submit_many([dict(prompt=wave[0]["prompt"],
                                       max_new_tokens=8,
                                       seed=poison_seed,
                                       use_eos_stop=False)])
        res = h.result(300)
        # quarantined after exactly 2 crash-correlated incarnations —
        # never resubmitted to take down the third replica
        assert res.finish_reason == "quarantined"
        assert h._rr.crashes == 2
        q = EVENT_LOG.recent(event="request_quarantined")
        assert q and q[-1]["crashes"] == 2
        assert router.quarantined_total == 1

        # both crashed replicas rebuilt; cluster back to 3/3
        assert _heal(router)
        assert sup.rebuilt_total == 2
        assert sorted(r.generation for r in router.replicas) == [0, 1, 1]
        snap = router.snapshot()
        assert snap["router"]["usable"] == 3
        assert snap["router"]["quarantined_total"] == 1

        # full capacity, zero post-warmup recompiles: the rebuilt
        # replicas were re-warmed off-rotation with workload-shaped
        # specs, so the serving window never pays a compile
        with no_recompiles():
            results = _run(router, wave)
        assert [list(r.tokens) for r in results] == ref
    finally:
        router.shutdown()
    for r in router.replicas:
        assert r.engine.sanitizer_report == []
    for reports in sup.incarnation_reports.values():
        for rep in reports:
            assert rep == []


# ---------------------------------------------------------------------------
# shipment I/O faults: keep-local fallback, balanced ledgers (satellite)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("site,event", [
    ("ship-export", "ship_export_failed"),
    ("ship-import", "ship_failed"),
])
def test_ship_io_fault_keeps_streams_bitwise(tiny, site, event):
    cfg, params = tiny
    specs = [dict(prompt=p, max_new_tokens=8, seed=i, use_eos_stop=False)
             for i, p in enumerate(_prompts(cfg, 3, seed=4))]
    ref = _reference_tokens(cfg, params, specs)
    streams = {i: [] for i in range(len(specs))}
    router = build_disagg_cluster(cfg, params, _ec(max_batch_size=2),
                                  prefill_replicas=1,
                                  decode_replicas=1).start()
    try:
        # first shipment hits the fault: export failure keeps the
        # request decoding on the prefill replica; import failure
        # reinstalls it there after the destination's unwind.  The
        # remaining shipments go through clean.
        chaos().fail_io(site)
        results = _run(router, [dict(s, on_token=streams[i].append)
                                for i, s in enumerate(specs)])
        assert ("fail_io", site) in chaos().events
        assert EVENT_LOG.recent(event=event)
        assert [list(r.tokens) for r in results] == ref
        for i, r in enumerate(results):
            assert streams[i] == list(map(int, r.tokens[r.prompt_len:]))
        if site == "ship-export":
            # the engine's own fallback counter; import failures are
            # observed (and recovered) router-side instead
            pre = router.replicas[0].engine
            assert pre.metrics.snapshot()["ship_failures_total"] >= 1
    finally:
        router.shutdown()
    # balanced ledgers on BOTH submeshes after the fallback
    for r in router.replicas:
        assert r.engine.sanitizer_report == []


# ---------------------------------------------------------------------------
# router backpressure -> 503 (satellite)
# ---------------------------------------------------------------------------

def test_router_queue_full_surfaces_as_503(tiny):
    from megatron_llm_tpu.generation.server import GenerationService
    from megatron_llm_tpu.tokenizer.tokenizer import NullTokenizer

    cfg, params = tiny
    svc = GenerationService(cfg, params,
                            NullTokenizer(vocab_size=cfg.vocab_size),
                            max_batch_size=2, engine_max_seq_len=64,
                            replicas=2, router=True)
    try:
        svc.engine.drain(timeout=60)  # all replicas draining
        EVENT_LOG.clear()
        status, resp = svc.handle({"prompts": ["3 4 5"],
                                   "tokens_to_generate": 4})
        assert status == 503
        assert resp["retry_after"] >= 1  # -> Retry-After header
        assert "draining" in resp["message"]
        full = EVENT_LOG.recent(event="router_queue_full")
        assert full and full[-1]["reason"] == "draining"
    finally:
        svc.close()


# ---------------------------------------------------------------------------
# deadline-aware failover (satellite)
# ---------------------------------------------------------------------------

def test_failover_expires_dead_budget_instead_of_resubmitting(tiny):
    cfg, params = tiny
    # slow probe: by the time the crash is detected, the request's
    # wall-clock budget is long gone — the old behavior resubmitted it
    # anyway, burning a slot on a dead-on-arrival retry
    router = build_cluster(
        cfg, params, _ec(sanitize=False), replicas=2,
        router_config=RouterConfig(probe_interval_s=0.5)).start()
    try:
        [h] = router.submit_many([dict(prompt=[1, 2, 3, 4],
                                       max_new_tokens=58,
                                       deadline_s=0.25, seed=0,
                                       use_eos_stop=False)])
        victim = h._rr.replica
        victim.engine.shutdown(timeout=30)  # crash before the deadline
        res = h.result(120)
        assert res.finish_reason == "timeout"
        snap = router.snapshot()
        assert snap["router"]["resubmitted_total"] == 0
        exp = EVENT_LOG.recent(event="failover_expired")
        assert exp and exp[-1]["replica"] == victim.id
    finally:
        router.shutdown()


def test_failover_passes_remaining_deadline(tiny):
    cfg, params = tiny
    router = build_cluster(
        cfg, params, _ec(sanitize=False), replicas=2,
        router_config=RouterConfig(probe_interval_s=0.02)).start()
    try:
        [h] = router.submit_many([dict(prompt=[1, 2, 3, 4],
                                       max_new_tokens=40,
                                       deadline_s=120.0, seed=0,
                                       use_eos_stop=False)])
        rr = h._rr
        original = rr.deadline
        assert original is not None
        router.kill_replica(rr.replica.id)
        if not rr.done_event.is_set():
            # the resubmitted engine request carries the ORIGINAL
            # absolute deadline (remaining budget), not a fresh 120s
            assert rr.handle._req.deadline == pytest.approx(original,
                                                            abs=1.0)
        assert h.result(120).finish_reason in ("length", "stop")
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# compound-fault chaos soak (slow tier; the CI chaos job runs it)
# ---------------------------------------------------------------------------

def test_chaos_soak_compound_faults(tiny):
    from megatron_llm_tpu.serving.bench import run_chaos_soak_bench

    cfg, params = tiny
    # hang_timeout_s must clear the worst-case iteration latency of 3
    # schedulers sharing the host CPU, or slow-but-healthy iterations
    # trip the watchdog (docs/robustness.md: sizing the hang timeout)
    out = run_chaos_soak_bench(cfg, params, num_requests=64, gen_len=10,
                               slots=2, max_prompt_len=32, replicas=3,
                               n_adapters=2, rank=4, draft_len=2,
                               hang_timeout_s=2.0, hang_s=6.0, seed=0)
    # every accepted token delivered exactly once, across every crash,
    # replay, shipment, and migration
    assert out["serving_chaos_delivery_violations"] == 0
    # ledgers balance on all incarnations — live and dead
    assert out["serving_chaos_leaked_blocks"] == 0
    # the cluster ends at full strength, with rebuilt generations
    assert out["serving_chaos_ended_full_strength"]
    assert out["serving_chaos_replicas_rebuilt"] >= 2
    assert out["serving_chaos_watchdog_trips"] >= 1
    assert {"serve-step", "serve-dispatch"} <= \
        set(out["serving_chaos_fired"])
    reasons = out["serving_chaos_finish_reasons"]
    assert set(reasons) <= {"length", "stop", "quarantined", "timeout"}
    # the storm may legitimately quarantine a few crash-correlated
    # bystanders; the overwhelming majority completes normally
    assert reasons.get("length", 0) + reasons.get("stop", 0) >= 56
