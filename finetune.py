#!/usr/bin/env python
"""Main training entry point: pretrain / finetune / instruction-tune
Llama 1/2, Code Llama, Falcon and GPT on TPU.

TPU-native counterpart of the reference driver (finetune.py:252-265 →
initialize_megatron → pretrain): argparse groups mirror the reference's
argument groups (megatron/arguments.py:15-35), resolved into the typed
``RuntimeConfig``, then handed to ``megatron_llm_tpu.training.driver.
pretrain``.

Examples:
  python finetune.py --model llama2 --model_size 7b \\
      --data_path data/corpus_text_document --tokenizer_type sentencepiece \\
      --tokenizer_model tokenizer.model --save ckpts/ --train_iters 1000 \\
      --global_batch_size 64 --micro_batch_size 4 --tp 8 --sequence_parallel
  python finetune.py --model tiny --mock_data --train_iters 10   # smoke run
"""

from __future__ import annotations

import argparse
import os
import sys


def _apply_platform_env() -> None:
    """Honor JAX_PLATFORMS even when a sitecustomize module already pinned
    the platform programmatically (axon TPU tunnels do); mirrors the test
    bootstrap in tests/conftest.py."""
    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    import jax
    from jax._src import xla_bridge as _xb

    if getattr(_xb, "_backends", None):
        import jax.extend.backend

        jax.extend.backend.clear_backends()
    jax.config.update("jax_platforms", want)


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)

    g = p.add_argument_group("model")
    g.add_argument("--model", default="llama2",
                   choices=["llama", "llama2", "llama3", "llama3.1",
                            "codellama", "falcon", "gpt", "tiny"])
    g.add_argument("--model_size", default="7b")
    g.add_argument("--seq_length", type=int, default=None)
    g.add_argument("--rope_scaling_factor", type=float, default=1.0)
    g.add_argument("--rope_scaling_type", default=None,
                   choices=["linear", "llama3", "yarn"],
                   help="RoPE scaling style (with --rope_scaling_factor); "
                        "llama3/yarn also need --rope_original_max_positions")
    g.add_argument("--rope_original_max_positions", type=int, default=None)
    g.add_argument("--num_experts", type=int, default=0,
                   help="MoE experts per layer (0 = dense)")
    g.add_argument("--moe_top_k", type=int, default=2)
    g.add_argument("--moe_capacity_factor", type=float, default=1.25)
    g.add_argument("--moe_aux_loss_coeff", type=float, default=0.01)
    g.add_argument("--params_dtype", default="bfloat16",
                   choices=["float32", "bfloat16", "float16"])
    g.add_argument("--attention_impl", default="flash",
                   choices=["flash", "dot"])
    g.add_argument("--recompute", default="selective",
                   choices=["none", "selective", "full"])
    g.add_argument("--quantize_matmuls", default="none",
                   choices=["none", "int8"],
                   help="W8A8 projection matmuls on the int8 MXU with "
                        "straight-through backward (the TE-FP8 analogue, "
                        "ref transformer.py:932-951)")
    g.add_argument("--hidden_dropout", type=float, default=None,
                   help="residual dropout rate (default: model preset)")
    g.add_argument("--lima_dropout", action="store_true",
                   help="layer-dependent dropout ramp 0->hidden_dropout "
                        "(LIMA, reference transformer.py:964-971)")
    g.add_argument("--drop_path_rate", type=float, default=0.0,
                   help="stochastic-depth rate at the last layer "
                        "(reference DropPath, transformer.py:43-64)")

    g = p.add_argument_group("lora")
    g.add_argument("--lora_rank", type=int, default=0,
                   help="train a LoRA adapter of this rank against the "
                        "frozen base model instead of full finetuning "
                        "(0 = off); checkpoints are adapter-only and "
                        "servable via serving/adapters/")
    g.add_argument("--lora_targets", nargs="*", default=None,
                   help="projections to adapt (default: wq wv); choose "
                        "from wq wk wv wo w_gate w_up w_down")
    g.add_argument("--lora_alpha", type=float, default=None,
                   help="LoRA alpha (default: rank, i.e. scale 1.0)")

    g = p.add_argument_group("parallelism")
    g.add_argument("--tp", "--tensor_parallel", type=int, default=1,
                   dest="tp")
    g.add_argument("--pp", "--pipeline_parallel", type=int, default=1,
                   dest="pp")
    g.add_argument("--dp", "--data_parallel", type=int, default=0, dest="dp",
                   help="0 = infer from device count / (tp*pp*cp)")
    g.add_argument("--ep", "--expert_parallel", type=int, default=1,
                   help="expert-parallel axis size (MoE)")
    g.add_argument("--cp_layout", "--context_parallel_layout",
                   default="contiguous", choices=["contiguous", "zigzag"],
                   help="zigzag balances causal ring-attention work "
                        "(~2x faster cp attention; pp=1 only)")
    g.add_argument("--cp", "--context_parallel", type=int, default=1,
                   dest="cp")
    g.add_argument("--virtual_pipeline_stages", type=int, default=1)
    g.add_argument("--pipeline_remat_window", type=int, default=0,
                   help="checkpoint the pipeline tick loop in windows of W "
                        "ticks: bounds activation memory at large "
                        "grad-accum counts (M>=64) for ~+25%% FLOPs; "
                        "0 = off, -1 = memory-minimizing auto choice; "
                        "with vpp>1 needs num_microbatches %% pp == 0")
    g.add_argument("--sequence_parallel", action="store_true")
    g.add_argument("--use_distributed_optimizer", action="store_true")

    g = p.add_argument_group("training")
    g.add_argument("--train_iters", type=int, default=1000)
    g.add_argument("--micro_batch_size", type=int, default=1)
    g.add_argument("--global_batch_size", type=int, default=1)
    g.add_argument("--rampup_batch_size", type=int, nargs=3, default=None)
    g.add_argument("--seed", type=int, default=1234)
    g.add_argument("--lr", type=float, default=3e-4)
    g.add_argument("--min_lr", type=float, default=3e-5)
    g.add_argument("--lr_decay_style", default="cosine",
                   choices=["constant", "linear", "cosine",
                            "inverse-square-root"])
    g.add_argument("--lr_warmup_iters", type=int, default=0)
    g.add_argument("--weight_decay", type=float, default=0.1)
    g.add_argument("--clip_grad", type=float, default=1.0)
    g.add_argument("--adam_beta1", type=float, default=0.9)
    g.add_argument("--adam_beta2", type=float, default=0.95)
    g.add_argument("--optimizer", default="adamw", choices=["adamw", "sgd"])
    g.add_argument("--skip_iters", type=int, nargs="*", default=())

    g = p.add_argument_group("checkpointing")
    g.add_argument("--save", default=None)
    g.add_argument("--load", default=None)
    g.add_argument("--save_interval", type=int, default=1000)
    g.add_argument("--use_checkpoint_args", action="store_true")

    g = p.add_argument_group("data")
    g.add_argument("--data_path", nargs="*", default=None,
                   help="corpus prefix(es), optionally weighted: "
                        "[w1 prefix1 w2 prefix2 ...]")
    g.add_argument("--split", default="969,30,1")
    g.add_argument("--instruction_data", action="store_true",
                   help="role-tagged instruction dataset "
                        "(<prefix>_text/_role pairs)")
    g.add_argument("--scalar_loss_mask", type=float, default=0.0)
    g.add_argument("--mock_data", action="store_true",
                   help="synthetic random tokens (smoke tests)")
    g.add_argument("--data_cache_dir", default=None)

    g = p.add_argument_group("tokenizer")
    g.add_argument("--tokenizer_type", default="null")
    g.add_argument("--tokenizer_model", default=None)
    g.add_argument("--vocab_extra_ids_list", nargs="*", default=None)

    g = p.add_argument_group("eval/logging")
    g.add_argument("--eval_interval", type=int, default=1000)
    g.add_argument("--eval_iters", type=int, default=10)
    g.add_argument("--log_interval", type=int, default=10)
    g.add_argument("--metrics", nargs="*", default=())
    g.add_argument("--tensorboard_dir", default=None)
    g.add_argument("--wandb_project", default=None)
    g.add_argument("--wandb_name", default=None)
    g.add_argument("--profile_dir", default=None,
                   help="write a jax.profiler device trace of a few "
                        "steady-state iterations here (TensorBoard "
                        "profile plugin viewable)")
    g.add_argument("--profile_step_start", type=int, default=11)
    g.add_argument("--profile_step_end", type=int, default=13)
    g.add_argument("--exit_interval", type=int, default=None)
    g.add_argument("--exit_duration_mins", type=float, default=None)

    return p.parse_args(argv)


def build_config(args):
    import jax

    from megatron_llm_tpu.config import (
        OptimizerConfig,
        ParallelConfig,
        RuntimeConfig,
        TrainConfig,
        codellama_config,
        falcon_config,
        gpt_config,
        llama1_config,
        llama2_config,
        llama3_config,
        llama31_config,
        tiny_config,
    )

    overrides = dict(
        params_dtype=args.params_dtype,
        attention_impl=args.attention_impl,
        recompute=args.recompute,
        quantize_matmuls=args.quantize_matmuls,
    )
    if args.seq_length:
        overrides["seq_length"] = args.seq_length
    if args.rope_scaling_factor != 1.0:
        overrides["rope_scaling_factor"] = args.rope_scaling_factor
    if args.rope_scaling_type:
        overrides["rope_scaling_type"] = args.rope_scaling_type
    if args.rope_original_max_positions:
        overrides["rope_original_max_positions"] = \
            args.rope_original_max_positions
    if args.hidden_dropout is not None:
        overrides["hidden_dropout"] = args.hidden_dropout
    if args.lima_dropout:
        if not args.hidden_dropout:
            raise SystemExit(
                "--lima_dropout ramps 0 -> hidden_dropout across layers, "
                "but hidden_dropout is 0 (the preset default) - pass a "
                "nonzero --hidden_dropout for it to have any effect")
        overrides["lima_dropout"] = True
    if args.drop_path_rate:
        overrides["drop_path_rate"] = args.drop_path_rate
    if args.num_experts:
        overrides.update(
            num_experts=args.num_experts, moe_top_k=args.moe_top_k,
            moe_capacity_factor=args.moe_capacity_factor,
            moe_aux_loss_coeff=args.moe_aux_loss_coeff)
    builders = {
        "llama": lambda: llama1_config(args.model_size, **overrides),
        "llama2": lambda: llama2_config(args.model_size, **overrides),
        "llama3": lambda: llama3_config(args.model_size, **overrides),
        "llama3.1": lambda: llama31_config(args.model_size, **overrides),
        "codellama": lambda: codellama_config(args.model_size, **overrides),
        "falcon": lambda: falcon_config(args.model_size, **overrides),
        "gpt": lambda: gpt_config(args.model_size, **overrides),
        "tiny": lambda: tiny_config(**overrides),
    }
    model = builders[args.model]()
    # check the effective factor (preset may supply it, e.g. llama3.1's 8.0)
    if args.rope_scaling_type and model.rope_scaling_factor == 1.0:
        raise SystemExit(
            "--rope_scaling_type has no effect with rope_scaling_factor=1.0 "
            "— pass --rope_scaling_factor (or a preset that sets one)")

    dp = args.dp
    if dp <= 0:
        denom = args.tp * args.pp * args.cp * args.ep
        dp = max(1, len(jax.devices()) // denom)
    parallel = ParallelConfig(
        data_parallel=dp,
        pipeline_parallel=args.pp,
        tensor_parallel=args.tp,
        context_parallel=args.cp,
        context_parallel_layout=args.cp_layout,
        expert_parallel=args.ep,
        virtual_pipeline_stages=args.virtual_pipeline_stages,
        pipeline_remat_window=args.pipeline_remat_window,
        sequence_parallel=args.sequence_parallel,
        use_distributed_optimizer=args.use_distributed_optimizer,
        num_microbatches=max(
            1, args.global_batch_size // (args.micro_batch_size * dp)),
    )
    optimizer = OptimizerConfig(
        optimizer=args.optimizer,
        lr=args.lr,
        min_lr=args.min_lr,
        weight_decay=args.weight_decay,
        adam_beta1=args.adam_beta1,
        adam_beta2=args.adam_beta2,
        clip_grad=args.clip_grad,
        lr_decay_style=args.lr_decay_style,
        lr_warmup_iters=args.lr_warmup_iters,
    )
    train = TrainConfig(
        train_iters=args.train_iters,
        micro_batch_size=args.micro_batch_size,
        global_batch_size=args.global_batch_size,
        rampup_batch_size=tuple(args.rampup_batch_size)
        if args.rampup_batch_size else None,
        seq_length=args.seq_length or model.seq_length,
        seed=args.seed,
        eval_interval=args.eval_interval,
        eval_iters=args.eval_iters,
        save=args.save,
        load=args.load,
        save_interval=args.save_interval,
        log_interval=args.log_interval,
        tensorboard_dir=args.tensorboard_dir,
        wandb_project=args.wandb_project,
        wandb_name=args.wandb_name,
        exit_interval=args.exit_interval,
        profile_dir=args.profile_dir,
        profile_step_start=args.profile_step_start,
        profile_step_end=args.profile_step_end,
        exit_duration_mins=args.exit_duration_mins,
        data_path=args.data_path,
        split=args.split,
        metrics=tuple(args.metrics),
        skip_iters=tuple(args.skip_iters),
    )
    cfg = RuntimeConfig(model=model, parallel=parallel, optimizer=optimizer,
                        train=train)

    # --use_checkpoint_args: config wins from the checkpoint
    # (reference checkpointing.py:476-559, hook at initialize.py:41-43)
    if args.use_checkpoint_args and args.load:
        from megatron_llm_tpu.checkpointing import load_config_from_checkpoint

        saved = load_config_from_checkpoint(args.load)
        cfg = RuntimeConfig(model=saved.model, parallel=saved.parallel,
                            optimizer=saved.optimizer, train=train)
    return cfg.validate()


class _MockDataset:
    """Deterministic random-token dataset for smoke tests."""

    def __init__(self, vocab_size: int, seq_length: int, n: int = 4096,
                 seed: int = 0):
        self.vocab = vocab_size
        self.seq = seq_length
        self.n = n
        self.seed = seed

    def __len__(self):
        return self.n

    def __getitem__(self, idx):
        rng = __import__("numpy").random.default_rng(self.seed + idx)
        return {"text": rng.integers(
            0, self.vocab, self.seq + 1).astype("int64")}


def build_datasets(args, cfg):
    from megatron_llm_tpu.data.blendable_dataset import (
        BlendableDataset,
        parse_data_paths,
    )
    from megatron_llm_tpu.data.gpt_dataset import build_gpt_datasets
    from megatron_llm_tpu.data.instruction_dataset import (
        build_instruction_datasets,
    )

    if args.mock_data:
        ds = _MockDataset(cfg.model.vocab_size, cfg.train.seq_length)
        return ds, _MockDataset(cfg.model.vocab_size, cfg.train.seq_length,
                                n=256, seed=10_000), None
    assert args.data_path, "--data_path or --mock_data required"

    if args.instruction_data:
        assert len(args.data_path) == 1, (
            "instruction data takes a single prefix")
        return build_instruction_datasets(
            args.data_path[0], args.split, cfg.train.seq_length,
            cfg.train.seed, scalar_loss_mask=args.scalar_loss_mask)

    weights, prefixes = parse_data_paths(args.data_path)
    total_samples = cfg.train.train_iters * cfg.train.global_batch_size
    eval_samples = cfg.train.eval_iters * cfg.train.global_batch_size
    nums = [total_samples, eval_samples, eval_samples]
    per_prefix = [
        build_gpt_datasets(prefix, args.split, nums, cfg.train.seq_length,
                           cfg.train.seed, args.data_cache_dir)
        for prefix in prefixes
    ]
    out = []
    for i in range(3):
        # keep weights aligned with the prefixes that produced this split
        pairs = [(p[i], w) for p, w in zip(per_prefix, weights)
                 if p[i] is not None]
        if not pairs:
            out.append(None)
        elif len(pairs) == 1:
            out.append(pairs[0][0])
        else:
            out.append(BlendableDataset(
                [d for d, _ in pairs], [w for _, w in pairs], nums[i]))
    return tuple(out)


def main(argv=None) -> int:
    _apply_platform_env()
    args = parse_args(argv)
    cfg = build_config(args)

    from megatron_llm_tpu.training.driver import pretrain, print_rank_0

    eod = None
    if args.tokenizer_type and args.tokenizer_type != "null" \
            and args.tokenizer_model:
        from megatron_llm_tpu.tokenizer.tokenizer import build_tokenizer

        # accept both "--x a b" and the comma-joined "--x a,b" forms (the
        # preprocess tool documents the comma form)
        extra = args.vocab_extra_ids_list
        if extra:
            extra = [t for item in extra for t in item.split(",") if t]
        tok = build_tokenizer(args.tokenizer_type, args.tokenizer_model,
                              extra)
        eod = tok.eod
        if tok.vocab_size > cfg.model.vocab_size:
            # extra special tokens grew the tokenizer beyond the preset
            # model vocab (reference pads vocab from the tokenizer,
            # megatron/tokenizer/tokenizer.py:39-63) — grow the embedding
            # so the new ids are real rows, not clamped aliases.
            import dataclasses as _dc

            from megatron_llm_tpu.config import RuntimeConfig as _RC

            cfg = _RC(
                model=_dc.replace(cfg.model, vocab_size=tok.vocab_size),
                parallel=cfg.parallel, optimizer=cfg.optimizer,
                train=cfg.train).validate()
            print_rank_0(f" vocab grown to {tok.vocab_size} "
                         f"(tokenizer extra ids)")

    print_rank_0(f"model: {args.model} {args.model_size} | "
                 f"mesh: dp={cfg.parallel.data_parallel} "
                 f"pp={cfg.parallel.pipeline_parallel} "
                 f"cp={cfg.parallel.context_parallel} "
                 f"tp={cfg.parallel.tensor_parallel} | "
                 f"gbs={cfg.train.global_batch_size} "
                 f"seq={cfg.train.seq_length}")
    train_ds, valid_ds, test_ds = build_datasets(args, cfg)

    if args.lora_rank:
        # adapter-only finetune against a frozen base: the base comes
        # from --load (params-only restore; the optimizer state of a
        # full checkpoint is never read) or fresh init for smoke runs,
        # and --save receives an adapter-only checkpoint
        import jax as _jax

        from megatron_llm_tpu import checkpointing
        from megatron_llm_tpu.models import model as model_lib
        from megatron_llm_tpu.training.lora import lora_finetune

        if cfg.train.load:
            base = checkpointing.load_params_for_inference(
                cfg.train.load, cfg.model)
            print_rank_0(f" loaded frozen base from {cfg.train.load}")
        else:
            print_rank_0(" no --load: LoRA against a fresh random base "
                         "(smoke runs only)")
            base = model_lib.init_params(
                _jax.random.key(cfg.train.seed), cfg.model)
        lora_finetune(cfg, base, train_ds, rank=args.lora_rank,
                      targets=args.lora_targets, alpha=args.lora_alpha,
                      eod_token=eod, save=cfg.train.save)
        return 0

    pretrain(cfg, train_ds, valid_ds, test_ds, eod_token=eod)
    return 0


if __name__ == "__main__":
    sys.exit(main())
