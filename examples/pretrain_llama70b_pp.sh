#!/bin/bash
# Llama-2-70B on a v5p-256 pod slice: tp=8 x pp=8 x dp=4 — BASELINE.md
# config 5.  The pipelined schedule streams microbatches (embed at stage 0,
# CE head at the last stage inside the tick loop); docs/pipeline_memory.md
# gives the per-chip memory budget for this exact configuration (~14.5 GB
# of 95 GB HBM with full remat + ZeRO-1).  M = 512/(1*4) = 128 microbatches
# divides pp=8, so the tight interleaved schedule runs and the remat
# window bounds the O(M*vpp) boundary memory.
set -euo pipefail

# async-collective / overlap XLA flags (must precede backend init)
eval "$(python -m megatron_llm_tpu.initialize)"

python finetune.py \
    --model llama2 --model_size 70b \
    --load "${CKPT:-ckpts/llama2-70b}" --save ckpts/run70b \
    --data_path "$1" \
    --tokenizer_type sentencepiece --tokenizer_model "$2" \
    --tp 8 --pp 8 --dp 4 --virtual_pipeline_stages 2 \
    --pipeline_remat_window 16 \
    --sequence_parallel --use_distributed_optimizer \
    --params_dtype bfloat16 --attention_impl flash --recompute full \
    --micro_batch_size 1 --global_batch_size 512 \
    --seq_length 4096 --train_iters 1000 \
    --lr 1.5e-5 --lr_decay_style cosine --lr_warmup_iters 100 \
    --clip_grad 1.0 --log_interval 5
