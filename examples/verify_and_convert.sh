#!/bin/bash
# Trust path: HF → native conversion, logit verification, round-trip export.
set -euo pipefail
HF=${1:-meta-llama/Llama-2-7b-hf}

python -m megatron_llm_tpu.tools.checkpoint_util hf-to-native \
    --hf_path "$HF" --output ckpts/imported
python -m megatron_llm_tpu.tools.verify_correctness \
    --hf_path "$HF" --iters 10 --seq_length 512
python -m megatron_llm_tpu.tools.checkpoint_util native-to-hf \
    --load ckpts/imported --output export/hf --hf_base "$HF"
