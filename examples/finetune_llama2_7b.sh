#!/bin/bash
# Llama-2-7B finetune on one v5e/v5p host (8 chips): tp=8 + sequence
# parallelism + ZeRO-1 — the BASELINE.md headline configuration.
set -euo pipefail

CKPT=${CKPT:-ckpts/llama2-7b}
DATA=${DATA:-data/corpus_text_document}
TOKENIZER=${TOKENIZER:-tokenizer.model}

python finetune.py \
    --model llama2 --model_size 7b \
    --load "$CKPT" --save ckpts/run1 --save_interval 100 \
    --data_path "$DATA" \
    --tokenizer_type sentencepiece --tokenizer_model "$TOKENIZER" \
    --tp 8 --sequence_parallel --use_distributed_optimizer \
    --params_dtype bfloat16 --attention_impl flash --recompute selective \
    --micro_batch_size 4 --global_batch_size 1000 \
    --seq_length 1024 --train_iters 500 \
    --lr 2e-5 --min_lr 2e-6 --lr_decay_style cosine --lr_warmup_iters 50 \
    --weight_decay 0.1 --clip_grad 1.0 \
    --eval_interval 100 --eval_iters 10 --log_interval 10 \
    --metrics perplexity accuracy
