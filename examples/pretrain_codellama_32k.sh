#!/bin/bash
# Code-Llama style long-context training: 32k sequences via linear RoPE
# position interpolation (rope_scaling_factor = 32768/4096 = 8) + ring
# attention context parallelism over 4 chips + flash attention.
set -euo pipefail

python finetune.py \
    --model codellama --model_size 7b \
    --data_path "$1" \
    --tokenizer_type sentencepiece --tokenizer_model "$2" \
    --seq_length 32768 --rope_scaling_factor 8 \
    --cp 4 --tp 2 --sequence_parallel \
    --attention_impl flash --recompute full \
    --micro_batch_size 1 --global_batch_size 16 \
    --train_iters 1000 --lr 1e-5 --log_interval 5
