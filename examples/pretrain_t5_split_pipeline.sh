#!/bin/bash
# T5 pretraining through the encoder/decoder SPLIT-RANK pipeline
# (reference: pretrain_t5.py + pipeline_model_parallel_split_rank,
# megatron/core/parallel_state.py:110-112) — stages [0, split) hold the
# encoder stack, [split, pp) the decoder; the encoder output rides the
# ppermute ring into every decoder stage's cross-attention
# (parallel/pipeline_encdec.py, docs/parallelism.md).
#
# Mesh: dp2 x pp4 (split 2) on 8 chips; ZeRO-1 shards optimizer state
# over dp.  global_batch / (micro_batch * dp) becomes both the grad-accum
# count and the pipeline's microbatch count.
set -euo pipefail

python pretrain_t5.py \
    --data_path "${CORPUS:-data/t5_corpus}" \
    --tokenizer_model "${TOKENIZER:-t5-base}" \
    --hidden_size 1024 --num_layers 24 --num_decoder_layers 24 \
    --num_attention_heads 16 \
    --encoder_seq_length 512 --decoder_seq_length 128 \
    --micro_batch_size 2 --global_batch_size 64 \
    --data_parallel 2 --pipeline_parallel 4 --pipeline_split_rank 2 \
    --use_distributed_optimizer \
    --train_iters 100000 --lr 1e-4 \
    --save "${SAVE:-ckpts/t5-large}" --save_interval 2000
