#!/bin/bash
# Zero-shot LM eval: WikiText-103 perplexity and LAMBADA accuracy
# (reference: examples/evaluate_zeroshot_gpt.sh + tasks/zeroshot_gpt/).
set -euo pipefail

CKPT=${CKPT:-ckpts/llama2-7b}
TOKENIZER=${TOKENIZER:-tokenizer.model}

python -m megatron_llm_tpu.tasks.main --task wikitext \
    --load "$CKPT" --tokenizer_type sentencepiece \
    --tokenizer_model "$TOKENIZER" \
    --data_path "${WIKITEXT:-data/wikitext-103/wiki.test.tokens}"

python -m megatron_llm_tpu.tasks.main --task lambada \
    --load "$CKPT" --tokenizer_type sentencepiece \
    --tokenizer_model "$TOKENIZER" \
    --data_path "${LAMBADA:-data/lambada_test.jsonl}"
