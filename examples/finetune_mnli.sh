#!/bin/bash
# MNLI classification finetune from a pretrained BERT release checkpoint
# (reference: examples/finetune_mnli_distributed.sh + tasks/glue/mnli.py).
# Expects the GLUE MNLI distribution's TSV files as shipped.
set -euo pipefail

DATA=${DATA:-data/MNLI}
BERT_CKPT=${BERT_CKPT:-ckpts/bert-base}

python -m megatron_llm_tpu.tasks.main --task mnli \
    --train_data "$DATA/train.tsv" \
    --valid_data "$DATA/dev_matched.tsv" \
    --pretrained_checkpoint "$BERT_CKPT" \
    --tokenizer_model bert-base-uncased \
    --seq_length 128 --epochs 3 \
    --micro_batch_size 8 --global_batch_size 32 --lr 2e-5
