#!/bin/bash
# ORQA retriever eval on Natural Questions: top-k retrieval accuracy
# against gold answers (reference: examples/evaluate_retriever_nq.sh).
# The question embeddings come from the biencoder query tower; the
# evidence embeddings from the REALM indexer (pretrain_ict.py →
# models/realm_indexer.py).
set -euo pipefail

python -m megatron_llm_tpu.tasks.main --task orqa \
    --qa_file "${NQ:-data/nq-dev.tsv}" \
    --evidence_texts "${EVIDENCE:-data/wiki_blocks.jsonl}" \
    --embedding_path "${EMBED:-data/block_embeds.npz}" \
    --query_embeds "${QUERIES:-data/nq_query_embeds.npy}" \
    --top_ks 1 5 20 100 --match_type string
