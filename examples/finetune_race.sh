#!/bin/bash
# RACE multiple-choice finetune (reference:
# examples/finetune_race_distributed.sh + tasks/race/data.py).  Data dirs
# contain the RACE distribution's .txt JSON-lines files.
set -euo pipefail

DATA=${DATA:-data/RACE}
BERT_CKPT=${BERT_CKPT:-ckpts/bert-base}

python -m megatron_llm_tpu.tasks.main --task race \
    --train_data "$DATA/train/middle" "$DATA/train/high" \
    --valid_data "$DATA/dev/middle" "$DATA/dev/high" \
    --pretrained_checkpoint "$BERT_CKPT" \
    --tokenizer_model bert-base-uncased \
    --seq_length 512 --max_qa_length 128 --epochs 3 \
    --micro_batch_size 4 --global_batch_size 16 --lr 1e-5
