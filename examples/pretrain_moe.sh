#!/bin/bash
# Mixture-of-experts pretraining (capability beyond the reference fork):
# 8 experts sharded over ep=4, top-2 token-choice routing with capacity.
# Watch "moe dropped frac" / "moe load imbalance" in the training log to
# tune --moe_capacity_factor (dispatch memory is E-independent; see
# models/moe.py docstring).
set -euo pipefail

python finetune.py \
    --model llama2 --model_size 7b \
    --data_path "$1" \
    --tokenizer_type sentencepiece --tokenizer_model "$2" \
    --num_experts 8 --moe_top_k 2 --moe_capacity_factor 1.25 \
    --ep 4 --dp 2 --use_distributed_optimizer \
    --params_dtype bfloat16 --attention_impl flash --recompute selective \
    --micro_batch_size 2 --global_batch_size 256 \
    --seq_length 2048 --train_iters 1000 \
    --lr 3e-5 --log_interval 10
