#!/bin/bash
# REST text-generation server + a probe request.
set -euo pipefail
# Multi-chip serving: --tp N shards tensors; --pp M from a tp x pp
# training topology JOINS tp for serving (weights resident, tp*pp-way).
# --quantize int8 halves decode HBM traffic (weight-only, per-channel).
# SERVE_SPEC=pld turns on prompt-lookup speculative decoding for greedy
# requests (multi-token decode steps; docs/inference.md).
python -m megatron_llm_tpu.tools.run_text_generation_server \
    --load "${1:-ckpts/run1}" \
    --tokenizer_type sentencepiece --tokenizer_model "${2:-tokenizer.model}" \
    ${SERVE_TP:+--tp "$SERVE_TP"} ${SERVE_PP:+--pp "$SERVE_PP"} \
    ${SERVE_QUANT:+--quantize "$SERVE_QUANT"} \
    ${SERVE_KV_QUANT:+--kv_quant "$SERVE_KV_QUANT"} \
    ${SERVE_SPEC:+--speculative "$SERVE_SPEC"} \
    --port 5000 &
sleep 10
curl -X PUT localhost:5000/api -H 'Content-Type: application/json' \
    -d '{"prompts": ["The capital of France is"], "tokens_to_generate": 16}'
