#!/bin/bash
# REST text-generation server + a probe request.
set -euo pipefail
python -m megatron_llm_tpu.tools.run_text_generation_server \
    --load "${1:-ckpts/run1}" \
    --tokenizer_type sentencepiece --tokenizer_model "${2:-tokenizer.model}" \
    --port 5000 &
sleep 10
curl -X PUT localhost:5000/api -H 'Content-Type: application/json' \
    -d '{"prompts": ["The capital of France is"], "tokens_to_generate": 16}'
