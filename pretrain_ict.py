"""ICT (inverse cloze task) bi-encoder pretraining entry point
(reference: pretrain_ict.py).

Corpus: the sentence-per-item .bin/.idx format of pretrain_bert.py.

Example:
  python pretrain_ict.py --data_path corpus --vocab_size 30522 \
      --query_seq_length 64 --block_seq_length 256 --train_iters 1000
"""

from __future__ import annotations

import argparse

import jax

from megatron_llm_tpu.config import (
    ModelConfig, OptimizerConfig, ParallelConfig, RuntimeConfig, TrainConfig,
)
from megatron_llm_tpu.data.ict_dataset import ICTDataset, ICTSpecialTokens
from megatron_llm_tpu.data.indexed_dataset import MMapIndexedDataset
from megatron_llm_tpu.models import biencoder
from megatron_llm_tpu.training.driver import pretrain_custom


def get_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data_path", required=True)
    p.add_argument("--vocab_size", type=int, required=True)
    p.add_argument("--hidden_size", type=int, default=768)
    p.add_argument("--num_layers", type=int, default=12)
    p.add_argument("--num_attention_heads", type=int, default=12)
    p.add_argument("--query_seq_length", type=int, default=64)
    p.add_argument("--block_seq_length", type=int, default=256)
    p.add_argument("--projection_dim", type=int, default=128)
    p.add_argument("--shared_query_context_model", action="store_true")
    p.add_argument("--pooling", default="mean", choices=["cls", "mean"],
                   help="cls matches the reference (warm-started towers); "
                        "mean trains from scratch")
    p.add_argument("--micro_batch_size", type=int, default=8)
    p.add_argument("--global_batch_size", type=int, default=32)
    p.add_argument("--train_iters", type=int, default=1000)
    p.add_argument("--lr", type=float, default=1e-4)
    p.add_argument("--save", default=None)
    p.add_argument("--save_interval", type=int, default=500)
    p.add_argument("--log_interval", type=int, default=10)
    p.add_argument("--data_parallel", type=int, default=1)
    p.add_argument("--seed", type=int, default=1234)
    p.add_argument("--cls_id", type=int, default=None,
                   help="default: vocab_size-3")
    p.add_argument("--sep_id", type=int, default=None)
    p.add_argument("--pad_id", type=int, default=0)
    return p.parse_args(argv)


def main(argv=None):
    args = get_args(argv)
    model = ModelConfig(
        vocab_size=args.vocab_size,
        hidden_size=args.hidden_size,
        num_layers=args.num_layers,
        num_attention_heads=args.num_attention_heads,
        num_kv_heads=args.num_attention_heads,
        ffn_hidden_size=4 * args.hidden_size,
        max_position_embeddings=max(args.query_seq_length,
                                    args.block_seq_length),
        norm_type="layernorm", activation="gelu",
        position_embedding_type="absolute", use_bias=True,
        tie_embed_logits=True, tokentype_size=2,
        seq_length=args.block_seq_length,
    )
    cfg = RuntimeConfig(
        model=model,
        parallel=ParallelConfig(data_parallel=args.data_parallel),
        optimizer=OptimizerConfig(lr=args.lr, clip_grad=1.0),
        train=TrainConfig(
            train_iters=args.train_iters,
            micro_batch_size=args.micro_batch_size,
            global_batch_size=args.global_batch_size,
            seq_length=args.block_seq_length,
            save=args.save, save_interval=args.save_interval,
            log_interval=args.log_interval, seed=args.seed,
        ),
    ).validate()

    special = ICTSpecialTokens(
        cls=args.cls_id if args.cls_id is not None else args.vocab_size - 3,
        sep=args.sep_id if args.sep_id is not None else args.vocab_size - 2,
        pad=args.pad_id)
    ds = ICTDataset(
        MMapIndexedDataset(args.data_path),
        args.query_seq_length, args.block_seq_length, special,
        seed=args.seed)
    params = biencoder.init_biencoder_params(
        jax.random.key(args.seed), cfg.model,
        projection_dim=args.projection_dim,
        shared=args.shared_query_context_model)

    def loss_fn(rcfg, p, mb, rng, deterministic):
        return biencoder.retrieval_loss(rcfg.model, p, mb, rng,
                                        deterministic,
                                        pooling=args.pooling)

    return pretrain_custom(cfg, ds, params, loss_fn)


if __name__ == "__main__":
    main()
