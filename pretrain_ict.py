"""ICT (inverse cloze task) bi-encoder pretraining entry point
(reference: pretrain_ict.py).

Corpus: the sentence-per-item .bin/.idx format of pretrain_bert.py.

Example:
  python pretrain_ict.py --data_path corpus --vocab_size 30522 \
      --query_seq_length 64 --block_seq_length 256 --train_iters 1000
"""

from __future__ import annotations

import argparse

import jax

from megatron_llm_tpu.config import (
    ModelConfig, OptimizerConfig, ParallelConfig, RuntimeConfig, TrainConfig,
)
from megatron_llm_tpu.data.ict_dataset import ICTDataset, ICTSpecialTokens
from megatron_llm_tpu.data.indexed_dataset import MMapIndexedDataset
from megatron_llm_tpu.models import biencoder
from megatron_llm_tpu.training.driver import pretrain_custom


def get_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data_path", required=True)
    p.add_argument("--vocab_size", type=int, default=None)
    p.add_argument("--hidden_size", type=int, default=768)
    p.add_argument("--num_layers", type=int, default=12)
    p.add_argument("--num_attention_heads", type=int, default=12)
    p.add_argument("--query_seq_length", type=int, default=64)
    p.add_argument("--block_seq_length", type=int, default=256)
    p.add_argument("--projection_dim", type=int, default=128)
    p.add_argument("--shared_query_context_model", action="store_true")
    p.add_argument("--pooling", default="mean", choices=["cls", "mean"],
                   help="cls matches the reference (warm-started towers); "
                        "mean trains from scratch")
    p.add_argument("--remove_prob", type=float, default=0.9,
                   help="probability the query sentence is removed from its "
                        "block (1 - the reference's query_in_block_prob)")
    # accum == 1 by default: retrieval_loss contrasts within a microbatch,
    # so grad accumulation would shrink the in-batch-negative pool
    p.add_argument("--micro_batch_size", type=int, default=32)
    p.add_argument("--global_batch_size", type=int, default=32)
    p.add_argument("--train_iters", type=int, default=1000)
    p.add_argument("--lr", type=float, default=1e-4)
    p.add_argument("--save", default=None)
    p.add_argument("--save_interval", type=int, default=500)
    p.add_argument("--log_interval", type=int, default=10)
    p.add_argument("--data_parallel", type=int, default=1)
    p.add_argument("--tensor_parallel", type=int, default=1)
    p.add_argument("--use_distributed_optimizer", action="store_true",
                   help="ZeRO-1: shard optimizer state over dp")
    p.add_argument("--seed", type=int, default=1234)
    p.add_argument("--tokenizer_model", default=None,
                   help="HF tokenizer path/name: derives vocab + special "
                        "ids (otherwise pass --vocab_size and, for real "
                        "corpora, --cls_id/--sep_id)")
    p.add_argument("--cls_id", type=int, default=None,
                   help="default: tokenizer cls id, else vocab_size-4 "
                        "(pretrain_bert convention)")
    p.add_argument("--sep_id", type=int, default=None,
                   help="default: tokenizer sep id, else vocab_size-3")
    p.add_argument("--pad_id", type=int, default=None)
    return p.parse_args(argv)


def main(argv=None):
    args = get_args(argv)
    if args.tokenizer_model:
        from megatron_llm_tpu.tokenizer.tokenizer import build_tokenizer

        tok = build_tokenizer("huggingface", args.tokenizer_model)
        inner = tok.inner
        vocab = tok.vocab_size
        cls_id = (args.cls_id if args.cls_id is not None
                  else inner.cls_token_id)
        sep_id = (args.sep_id if args.sep_id is not None
                  else inner.sep_token_id)
        pad_id = (args.pad_id if args.pad_id is not None
                  else (inner.pad_token_id or 0))
    else:
        assert args.vocab_size, "--vocab_size required without "            "--tokenizer_model"
        vocab = args.vocab_size
        # same reserved-id convention as pretrain_bert.py's tokenizer-less
        # mode (cls=v-4, sep=v-3, mask=v-2)
        cls_id = args.cls_id if args.cls_id is not None else vocab - 4
        sep_id = args.sep_id if args.sep_id is not None else vocab - 3
        pad_id = args.pad_id if args.pad_id is not None else 0

    accum = args.global_batch_size // (args.micro_batch_size
                                       * args.data_parallel)
    if accum > 1:
        import warnings

        warnings.warn(
            f"grad accumulation ({accum} microbatches) shrinks the "
            f"in-batch-negative pool to micro_batch_size="
            f"{args.micro_batch_size} per contrastive softmax")

    model = ModelConfig(
        vocab_size=vocab,
        hidden_size=args.hidden_size,
        num_layers=args.num_layers,
        num_attention_heads=args.num_attention_heads,
        num_kv_heads=args.num_attention_heads,
        ffn_hidden_size=4 * args.hidden_size,
        max_position_embeddings=max(args.query_seq_length,
                                    args.block_seq_length),
        norm_type="layernorm", activation="gelu",
        position_embedding_type="absolute", use_bias=True,
        tie_embed_logits=True, tokentype_size=2,
        hidden_dropout=0.1, attention_dropout=0.1,
        seq_length=args.block_seq_length,
    )
    cfg = RuntimeConfig(
        model=model,
        parallel=ParallelConfig(data_parallel=args.data_parallel,
                                tensor_parallel=args.tensor_parallel,
                                use_distributed_optimizer=
                                args.use_distributed_optimizer),
        optimizer=OptimizerConfig(lr=args.lr, clip_grad=1.0),
        train=TrainConfig(
            train_iters=args.train_iters,
            micro_batch_size=args.micro_batch_size,
            global_batch_size=args.global_batch_size,
            seq_length=args.block_seq_length,
            save=args.save, save_interval=args.save_interval,
            log_interval=args.log_interval, seed=args.seed,
        ),
    ).validate()

    special = ICTSpecialTokens(cls=cls_id, sep=sep_id, pad=pad_id)
    ds = ICTDataset(
        MMapIndexedDataset(args.data_path),
        args.query_seq_length, args.block_seq_length, special,
        remove_prob=args.remove_prob, seed=args.seed)
    params = biencoder.init_biencoder_params(
        jax.random.key(args.seed), cfg.model,
        projection_dim=args.projection_dim,
        shared=args.shared_query_context_model,
        tp=args.tensor_parallel)
    specs = (biencoder.biencoder_param_specs(
                 cfg.model, cfg.parallel,
                 projection_dim=args.projection_dim,
                 shared=args.shared_query_context_model)
             if (args.tensor_parallel > 1
                 or args.use_distributed_optimizer) else None)

    def loss_fn(rcfg, p, mb, rng, deterministic):
        return biencoder.retrieval_loss(rcfg.model, p, mb, rng,
                                        deterministic,
                                        pooling=args.pooling)

    return pretrain_custom(cfg, ds, params, loss_fn,
                           param_specs=specs)


if __name__ == "__main__":
    main()
