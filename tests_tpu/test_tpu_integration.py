"""Opt-in real-TPU integration tier (SURVEY §4's hardware tier, the
analogue of the reference's torchrun GPU tests).

Run on a machine with a TPU attached:

    python -m pytest tests_tpu/ -q

Unlike tests/ (which pins an 8-device CPU mesh in its conftest), this
directory requires a real TPU and skips entirely on any other platform.
If you add timing assertions here, force host fetches (``float(...)``)
per measured call — ``block_until_ready`` can return early over tunneled
backends.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

if jax.devices()[0].platform != "tpu":  # pragma: no cover
    pytest.skip("requires a TPU device", allow_module_level=True)


def test_flash_kernel_matches_einsum_bf16():
    from megatron_llm_tpu.kernels.flash_attention import flash_attention
    from megatron_llm_tpu.ops.attention import dot_product_attention

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(2, 1024, 8, 64)), jnp.bfloat16)
    k = jnp.asarray(rng.normal(size=(2, 1024, 4, 64)), jnp.bfloat16)
    v = jnp.asarray(rng.normal(size=(2, 1024, 4, 64)), jnp.bfloat16)
    got = np.asarray(jax.jit(
        lambda a, b, c: flash_attention(a, b, c, causal=True))(q, k, v),
        np.float32)
    want = np.asarray(dot_product_attention(q, k, v, causal=True),
                      np.float32)
    assert np.max(np.abs(got - want)) < 3e-2  # bf16 kernel vs fp32 softmax


def test_flash_kernel_32k_long_context():
    """BASELINE config 4's hard part: 32k causal attention fwd+bwd."""
    from megatron_llm_tpu.kernels.flash_attention import flash_attention

    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.normal(size=(1, 32768, 4, 128)), jnp.bfloat16)
    k = jnp.asarray(rng.normal(size=(1, 32768, 4, 128)), jnp.bfloat16)
    v = jnp.asarray(rng.normal(size=(1, 32768, 4, 128)), jnp.bfloat16)
    g = jax.jit(jax.grad(lambda a, b, c: jnp.sum(
        flash_attention(a, b, c, causal=True).astype(jnp.float32) ** 2),
        argnums=(0, 1, 2)))
    gq, gk, gv = g(q, k, v)
    for arr in (gq, gk, gv):
        assert bool(jnp.isfinite(arr.astype(jnp.float32)).all())


def _train_setup(mb, seq, lr, **model_overrides):
    from megatron_llm_tpu.config import (
        OptimizerConfig, ParallelConfig, RuntimeConfig, TrainConfig,
        tiny_config,
    )
    from megatron_llm_tpu.training.driver import setup_train_state

    cfg = RuntimeConfig(
        model=tiny_config(params_dtype="bfloat16", **model_overrides),
        parallel=ParallelConfig(),
        optimizer=OptimizerConfig(lr=lr, clip_grad=1.0),
        train=TrainConfig(train_iters=10, micro_batch_size=mb,
                          global_batch_size=mb, seq_length=seq, save=None),
    ).validate()
    art = setup_train_state(cfg)
    toks = np.random.default_rng(0).integers(
        0, cfg.model.vocab_size, (1, mb, seq))
    batch = {
        "tokens": jnp.asarray(toks, jnp.int32),
        "labels": jnp.asarray(np.roll(toks, -1, -1), jnp.int32),
        "loss_mask": jnp.ones((1, mb, seq), jnp.float32),
    }
    return art, batch


def test_train_step_loss_decreases():
    # head_dim 16 (tiny_config) deliberately exercises a sub-128-lane
    # Pallas flash shape on hardware — validated passing on v5e
    art, batch = _train_setup(mb=4, seq=128, lr=1e-2,
                              attention_impl="flash")
    state = art.state
    losses = []
    for _ in range(8):
        state, m = art.step_fn(state, batch, jax.random.key(0))
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], losses


def test_moe_train_step_runs():
    art, batch = _train_setup(mb=2, seq=64, lr=1e-3,
                              num_experts=4, moe_top_k=2)
    state, m = art.step_fn(art.state, batch, None)
    state, m = art.step_fn(state, batch, None)  # re-donation
    assert np.isfinite(float(m["loss"]))


def test_generation_greedy():
    from megatron_llm_tpu.config import tiny_config
    from megatron_llm_tpu.generation.generation import generate_tokens
    from megatron_llm_tpu.models import model as model_lib

    cfg = tiny_config(params_dtype="bfloat16")
    params = model_lib.init_params(jax.random.key(0), cfg)
    buf = jnp.zeros((1, 16), jnp.int32).at[0, :4].set(
        jnp.asarray([5, 6, 7, 8]))
    out = generate_tokens(cfg, params, buf, jnp.asarray([4]),
                          use_eos_stop=False)
    toks = np.asarray(out.tokens)
    assert toks.shape == (1, 16)
    assert (toks[0, :4] == [5, 6, 7, 8]).all()


def test_flash_decode_kernel_parity_on_hw():
    """flash_decode (Pallas) vs a numpy reference on the real chip, across
    GQA/MQA configs.  Tolerance covers the MXU's default bf16-pass rounding
    of f32 operands; exact-math parity is covered in interpret mode by
    tests/kernels/test_flash_decode.py."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from megatron_llm_tpu.ops.attention import decode_attention

    rng = np.random.default_rng(0)
    for (h, kv, M, cl) in ((8, 8, 1024, 700), (8, 2, 512, 17),
                           (4, 1, 256, 255)):
        q = rng.normal(size=(2, 1, h, 128)).astype(np.float32)
        k = rng.normal(size=(2, kv, M, 128)).astype(np.float32)
        v = rng.normal(size=(2, kv, M, 128)).astype(np.float32)
        got = jax.jit(
            lambda q, k, v: decode_attention(q, k, v, jnp.int32(cl))
        )(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        g = h // kv
        qg = q.reshape(2, 1, kv, g, 128)
        want = np.zeros((2, 1, h, 128), np.float32)
        for b in range(2):
            for hh in range(kv):
                for gg in range(g):
                    s = (k[b, hh] @ qg[b, 0, hh, gg]) / np.sqrt(128)
                    s[cl + 1:] = -np.inf
                    p = np.exp(s - s.max())
                    p /= p.sum()
                    want[b, 0, hh * g + gg] = p @ v[b, hh]
        d = float(np.max(np.abs(np.asarray(got) - want)))
        assert d < 0.02, (h, kv, M, cl, d)


def test_training_mfu_floor():
    """Perf regression guard: the bench-shape train step must sustain
    >= 0.45 MFU on this chip (round-2 measured 0.53; round-1 0.42).  Run
    last-ish: it compiles the full 374M train step."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    import jax
    import pytest

    from bench import _train_point, chip_peak_flops

    kind = jax.devices()[0].device_kind
    if "v5 lite" not in kind.lower() and "v5e" not in kind.lower():
        # the 0.45 floor (and the mb=12 shape) is calibrated on v5e; a
        # faster chip would fail spuriously without retuning
        pytest.skip(f"MFU floor calibrated for v5e, running on {kind}")
    peak = chip_peak_flops(kind)
    tps, mfu, loss, _ = _train_point(1024, 12, "selective", 10, peak)
    assert mfu >= 0.45, (mfu, tps)
    assert loss < 12.0, loss


def test_int8_decode_speedup_and_parity():
    """Full int8 decode (weights + KV cache) on the real chip: throughput
    must not regress vs bf16 (the byte roofline predicts up to ~1.8× for
    the 374M bench model), and the Pallas int8 decode kernel must match an
    independently-computed einsum attention reference on the same int8
    cache.  (bf16-vs-int8 greedy token agreement is printed as a
    diagnostic only — on a random-init model every argmax is borderline,
    so quantization noise legitimately flips tokens.)"""
    import sys
    import time
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    import bench
    from megatron_llm_tpu.generation.generation import generate_tokens
    from megatron_llm_tpu.models import model as model_lib
    from megatron_llm_tpu.ops.quant import quantize_params

    import dataclasses

    b, prompt_len, gen_len = 8, 128, 256
    cfg = bench._bench_model(prompt_len + gen_len, "selective")
    qcfg = dataclasses.replace(cfg, kv_cache_quant="int8").validate()
    params = model_lib.init_params(jax.random.key(0), cfg)
    qparams = quantize_params(params)

    rng = np.random.default_rng(1)
    tokens = np.zeros((b, prompt_len + gen_len), np.int32)
    tokens[:, :prompt_len] = rng.integers(1, cfg.vocab_size,
                                          (b, prompt_len))
    tokens = jnp.asarray(tokens)
    lengths = jnp.full((b,), prompt_len, jnp.int32)

    def warm(c, p):
        out = generate_tokens(c, p, tokens, lengths, use_eos_stop=False)
        jax.device_get(out.tokens)  # compile + warm
        return out

    def timed(c, p):
        t0 = time.perf_counter()
        out = generate_tokens(c, p, tokens, lengths, use_eos_stop=False)
        jax.device_get(out.tokens)
        return b * gen_len / (time.perf_counter() - t0)

    # Tunnel latency drifts minute-to-minute (observed 1.7k-3.3k tok/s for
    # the SAME bf16 program across runs) — interleave the configs and
    # take best-of-3 each, so drift hits both alike.  The int8 byte-
    # savings comparison is against the COMPOSED bf16 path (int8 has no
    # fused decode-step kernel yet, so fused bf16 legitimately beats it —
    # measured 0.70x at this horizon after round 5's kernel landed).
    ccfg = dataclasses.replace(cfg, fused_decode=False).validate()
    out_bf16 = warm(cfg, params)            # fused kernel path
    out_comp = warm(ccfg, params)           # composed bf16 path
    out_int8 = warm(qcfg, qparams)          # int8 weights + int8 cache
    del out_comp
    bf16_trials, comp_trials, int8_trials = [], [], []
    for _ in range(3):
        bf16_trials.append(timed(cfg, params))
        comp_trials.append(timed(ccfg, params))
        int8_trials.append(timed(qcfg, qparams))
    tps_bf16 = max(bf16_trials)
    tps_comp = max(comp_trials)
    tps_int8 = max(int8_trials)
    print(f"decode tok/s: fused bf16={tps_bf16:.0f} "
          f"composed bf16={tps_comp:.0f} int8={tps_int8:.0f} "
          f"(int8/composed {tps_int8 / tps_comp:.2f}x)")
    # int8 must not CATASTROPHICALLY regress vs the path it actually
    # shares (composed) — e.g. the kernel silently falling back to a
    # several-x-slower path.  Coarse gate: tunnel jitter is ~10-15%;
    # clean-run ratios span ~1.0x at this 256-token horizon to 1.7-1.8x
    # at the 512-token horizon where cache reads matter more.
    assert tps_int8 >= 0.85 * tps_comp, (tps_comp, tps_int8)
    # and the fused kernel must actually be engaged and winning: it
    # measured 2.4x the composed path in-loop; 1.3x is the coarse floor
    assert tps_bf16 >= 1.3 * tps_comp, (tps_bf16, tps_comp)

    # fidelity: compare the Pallas int8 decode KERNEL against the einsum
    # int8 path on the SAME quantized cache — deterministic, isolates
    # kernel numerics.  (bf16-vs-int8 greedy token agreement is NOT a
    # sound assertion on a random-init model: near-uniform logits make
    # every argmax borderline, so quantization noise legitimately flips
    # tokens; printed above only as a diagnostic.)
    a = np.asarray(out_bf16.tokens)[:, prompt_len:prompt_len + 32]
    c = np.asarray(out_int8.tokens)[:, prompt_len:prompt_len + 32]
    print(f"int8-vs-bf16 greedy agreement (diagnostic): {(a == c).mean():.3f}")

    from megatron_llm_tpu.kernels.flash_decode import flash_decode_int8
    from megatron_llm_tpu.ops.kv_quant import quantize_rows

    kv, d, L = cfg.kv_heads, cfg.head_dim, 256
    g = cfg.num_attention_heads // kv
    r = np.random.default_rng(7)
    q = jnp.asarray(r.standard_normal((b, kv * g, d)), jnp.bfloat16)
    kc = quantize_rows(jnp.asarray(r.standard_normal((b, kv, L, d)),
                                   jnp.bfloat16))
    vc = quantize_rows(jnp.asarray(r.standard_normal((b, kv, L, d)),
                                   jnp.bfloat16))
    clen = 200
    kernel_out = flash_decode_int8(q, kc["q"], kc["scale"], vc["q"],
                                   vc["scale"], jnp.int32(clen))
    # Independent reference computed here (decode_attention would dispatch
    # to the same Pallas kernel on TPU — comparing against it is vacuous):
    # dequantize the cache and run plain masked softmax attention in fp32.
    kd = np.asarray(kc["q"], np.float32) * np.asarray(kc["scale"])[..., None]
    vd = np.asarray(vc["q"], np.float32) * np.asarray(vc["scale"])[..., None]
    qg = np.asarray(q, np.float32).reshape(b, kv, g, d)
    s = np.einsum("bkgd,bkld->bkgl", qg, kd) / np.sqrt(d)
    s[:, :, :, clen:] = -np.inf
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bkgl,bkld->bkgd", p, vd).reshape(b, kv * g, d)
    delta = np.abs(np.asarray(kernel_out, np.float32) - ref).max()
    print(f"int8 kernel vs independent einsum max|delta|: {delta:.5f}")
    assert delta < 0.05, delta
