"""Real-HF-checkpoint trust path (opt-in; reference
verify_correctness.py:113-173 + tests/test_llama_weights.py:91-118).

This environment has **zero egress** — the HF hub is unreachable — so no
real Llama/TinyLlama checkpoint can be downloaded here (verified: hub
requests hang).  The full harness is nevertheless wired and runs whenever a
real checkpoint directory is provided:

    MEGATRON_TPU_HF_MODEL=/path/to/hf_llama_dir \
        python -m pytest tests_tpu/test_real_weights.py -q

It then asserts the reference's published tolerances on the real weights:
avg(max|Δlogit|) ≤ 0.001 in fp32, avg abs err < 0.1 in bf16
(docs/guide/getting_started.md:154), plus native→HF→native round-trip
exactness and a real-tokenizer encode/decode round-trip.
"""

import os

import numpy as np
import pytest

MODEL_DIR = os.environ.get("MEGATRON_TPU_HF_MODEL")

needs_real_weights = pytest.mark.skipif(
    not MODEL_DIR,
    reason="set MEGATRON_TPU_HF_MODEL to a local HF Llama checkpoint dir "
           "(no egress in this environment: the hub is unreachable, so "
           "these only run where real weights are already on disk)")


@pytest.fixture(scope="module")
def hf_model():
    import transformers

    return transformers.AutoModelForCausalLM.from_pretrained(
        MODEL_DIR, torch_dtype="float32", attn_implementation="eager",
    ).eval()


@pytest.fixture(scope="module")
def converted(hf_model):
    from megatron_llm_tpu.tools import hf_interop

    cfg = hf_interop.config_from_hf(hf_model.config, family="llama",
                                    params_dtype="float32",
                                    attention_impl="dot",
                                    recompute="none")
    params = hf_interop.llama_from_hf(hf_model.state_dict(), cfg)
    return cfg, params


@needs_real_weights
def test_fp32_logit_match_reference_tolerance(hf_model, converted):
    """avg(max|Δlogit|) ≤ 0.001 over random batches — the exact gate of the
    reference's tests/test_llama_weights.py:117."""
    from megatron_llm_tpu.tools.verify_correctness import (
        _random_batches, verify)

    cfg, params = converted
    batches = _random_batches(cfg.vocab_size, iters=4, batch_size=1,
                              seq_length=min(
                                  512, cfg.max_position_embeddings))
    report = verify(cfg, params, hf_model, batches, tolerance=1e-3)
    print("real-weights fp32:", {k: v for k, v in report.items()
                                 if k != "steps"})
    assert report["passed"], report


@needs_real_weights
def test_bf16_tolerance(hf_model, converted):
    """avg abs err < 0.1 in bf16 (docs/guide/getting_started.md:154)."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from megatron_llm_tpu.models import model as model_lib

    cfg, params = converted
    bcfg = dataclasses.replace(cfg, params_dtype="bfloat16")
    bparams = jax.tree.map(lambda x: jnp.asarray(x, jnp.bfloat16), params)
    tokens = np.random.default_rng(0).integers(
        0, cfg.vocab_size, (1, 256))
    import torch

    with torch.no_grad():
        want = hf_model(torch.tensor(tokens)).logits.float().numpy()
    got = np.asarray(jax.jit(
        lambda p, t: model_lib.forward(bcfg, p, t))(
            bparams, jnp.asarray(tokens)), np.float32)
    got = got[..., : cfg.vocab_size]
    err = float(np.mean(np.abs(got - want)))
    print("real-weights bf16 avg abs err:", err)
    assert err < 0.1, err


@needs_real_weights
def test_roundtrip_native_hf_native(hf_model, converted):
    from megatron_llm_tpu.tools import hf_interop

    cfg, params = converted
    sd = hf_interop.llama_to_hf(params, cfg)
    params2 = hf_interop.llama_from_hf(sd, cfg)
    import jax

    for (pa, a), (_, b) in zip(jax.tree_util.tree_leaves_with_path(params),
                               jax.tree_util.tree_leaves_with_path(params2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=str(pa))


@needs_real_weights
def test_real_tokenizer_roundtrip():
    tok_file = os.path.join(MODEL_DIR, "tokenizer.model")
    if not os.path.exists(tok_file):
        pytest.skip("checkpoint has no sentencepiece tokenizer.model")
    from megatron_llm_tpu.tokenizer.tokenizer import build_tokenizer

    tok = build_tokenizer("sentencepiece", tok_file)
    text = "The quick brown fox jumps over 13 lazy dogs — naïve café."
    ids = tok.tokenize(text)
    assert tok.detokenize(ids).strip() == text


# ---------------------------------------------------------------------------
# Offline fallback: full-WIDTH Llama-2-7B dims (reduced depth), random
# weights.  Not a substitute for real weights, but it exercises the exact
# production matmul shapes (h=4096, 32 heads, ffn=11008, vocab=32000)
# through the converter + forward on hardware — the strongest trust
# evidence obtainable with zero egress.
# ---------------------------------------------------------------------------


def test_full_width_llama_dims_parity():
    import torch
    import transformers

    import jax
    import jax.numpy as jnp

    from megatron_llm_tpu.tools import hf_interop
    from megatron_llm_tpu.models import model as model_lib

    hf_cfg = transformers.LlamaConfig(
        vocab_size=32000, hidden_size=4096, intermediate_size=11008,
        num_hidden_layers=2, num_attention_heads=32,
        num_key_value_heads=32, max_position_embeddings=4096,
        rms_norm_eps=1e-5, rope_theta=10000.0,
        tie_word_embeddings=False, attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf_model = transformers.LlamaForCausalLM(hf_cfg).eval()

    cfg = hf_interop.config_from_hf(hf_cfg, family="llama",
                                    params_dtype="float32",
                                    attention_impl="dot",
                                    recompute="none")
    params = hf_interop.llama_from_hf(hf_model.state_dict(), cfg)

    tokens = np.random.default_rng(1).integers(0, 32000, (1, 128))
    with torch.no_grad():
        want = hf_model(torch.tensor(tokens)).logits.float().numpy()
    # TPU fp32 matmuls default to fast bf16-based passes (~1e-1 error at
    # h=4096); the trust path needs true fp32 MXU passes
    with jax.default_matmul_precision("highest"):
        got = np.asarray(jax.jit(
            lambda p, t: model_lib.forward(cfg, p, t))(
                params, jnp.asarray(tokens)))[..., :32000]
    diff = float(np.max(np.abs(got - want)))
    print("full-width llama dims max|Δlogit|:", diff)
    # reference gate for real fp32 weights is avg(max) ≤ 1e-3; random
    # full-width weights accumulate slightly more fp32 noise
    assert diff < 5e-3, diff
