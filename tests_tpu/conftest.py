"""Real-TPU tier bootstrap: fail fast when the accelerator is
unreachable.

``jax.devices()`` hangs indefinitely inside a C call when the axon
tunnel degrades (observed live: a silent 25+ minute wedge) — and the
test modules here call it at import, i.e. during collection.  Probe the
backend with bench.py's bounded subprocess probe at conftest import and
ignore this directory's collection when no TPU answers, so only the
hardware tier is skipped (a bare ``pytest`` from the repo root still
runs the CPU tiers and keeps their exit status).
"""

import os
import sys
import warnings

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import bench  # noqa: E402  (repo-root module; same probe as the driver)

_PROBE_TIMEOUT = int(os.environ.get("TPU_PROBE_TIMEOUT_S", "240"))

collect_ignore_glob: list = []

try:
    _kind = bench._detect_device(timeout_s=_PROBE_TIMEOUT)
    if "tpu" not in _kind.lower():
        raise RuntimeError(f"first device is {_kind!r}, not a TPU")
except (TimeoutError, RuntimeError, OSError) as e:
    warnings.warn(
        f"tests_tpu: skipping the hardware tier — {e}", stacklevel=1)
    collect_ignore_glob = ["test_*.py"]
