"""T5 span-corruption pretraining entry point (reference: pretrain_t5.py).

Same sentence-per-item .bin/.idx corpus as pretrain_bert.py.

Example:
  python pretrain_t5.py --data_path corpus --vocab_size 32128 \
      --encoder_seq_length 512 --decoder_seq_length 114 --train_iters 1000
"""

from __future__ import annotations

import argparse

import jax

from megatron_llm_tpu.config import (
    ModelConfig, OptimizerConfig, ParallelConfig, RuntimeConfig, TrainConfig,
)
from megatron_llm_tpu.data.indexed_dataset import MMapIndexedDataset
from megatron_llm_tpu.data.t5_dataset import T5Dataset, T5SpecialTokens
from megatron_llm_tpu.models import encdec
from megatron_llm_tpu.training.driver import pretrain_custom


def get_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data_path", required=True)
    p.add_argument("--vocab_size", type=int, default=None,
                   help="override (skips loading the tokenizer); sentinels "
                        "then fall back to the top vocab ids and "
                        "pad==bos==0, eos=1")
    p.add_argument("--tokenizer_model", default=None,
                   help="HF tokenizer (e.g. t5-small): derives vocab size, "
                        "bos/eos/pad and the <extra_id_i> sentinel ids")
    p.add_argument("--hidden_size", type=int, default=768)
    p.add_argument("--num_layers", type=int, default=12)
    p.add_argument("--num_decoder_layers", type=int, default=None)
    p.add_argument("--num_attention_heads", type=int, default=12)
    p.add_argument("--encoder_seq_length", type=int, default=512)
    p.add_argument("--decoder_seq_length", type=int, default=128)
    p.add_argument("--micro_batch_size", type=int, default=4)
    p.add_argument("--global_batch_size", type=int, default=32)
    p.add_argument("--train_iters", type=int, default=1000)
    p.add_argument("--lr", type=float, default=1e-4)
    p.add_argument("--save", default=None)
    p.add_argument("--save_interval", type=int, default=500)
    p.add_argument("--log_interval", type=int, default=10)
    p.add_argument("--data_parallel", type=int, default=1)
    p.add_argument("--tensor_parallel", type=int, default=1)
    p.add_argument("--pipeline_parallel", type=int, default=1,
                   help="encoder/decoder split-rank pipeline (reference: "
                        "pipeline_model_parallel_split_rank)")
    p.add_argument("--pipeline_split_rank", type=int, default=None,
                   help="stages holding the encoder (default pp // 2)")
    p.add_argument("--use_distributed_optimizer", action="store_true",
                   help="ZeRO-1: shard optimizer state over dp")
    p.add_argument("--seed", type=int, default=1234)
    p.add_argument("--masked_lm_prob", type=float, default=0.15)
    return p.parse_args(argv)


def t5_runtime_config(args) -> RuntimeConfig:
    model = ModelConfig(
        vocab_size=args.vocab_size,
        hidden_size=args.hidden_size,
        num_layers=args.num_layers,
        num_decoder_layers=args.num_decoder_layers,
        num_attention_heads=args.num_attention_heads,
        num_kv_heads=args.num_attention_heads,
        ffn_hidden_size=4 * args.hidden_size,
        max_position_embeddings=max(args.encoder_seq_length,
                                    args.decoder_seq_length),
        norm_type="layernorm",
        activation="gelu",
        position_embedding_type="absolute",
        use_bias=True,
        tie_embed_logits=True,
        seq_length=args.encoder_seq_length,
    )
    accum = args.global_batch_size // (args.micro_batch_size
                                       * args.data_parallel)
    return RuntimeConfig(
        model=model,
        parallel=ParallelConfig(data_parallel=args.data_parallel,
                                tensor_parallel=args.tensor_parallel,
                                pipeline_parallel=args.pipeline_parallel,
                                pipeline_split_rank=args.pipeline_split_rank,
                                num_microbatches=accum,
                                use_distributed_optimizer=
                                args.use_distributed_optimizer),
        optimizer=OptimizerConfig(lr=args.lr, clip_grad=1.0),
        train=TrainConfig(
            train_iters=args.train_iters,
            micro_batch_size=args.micro_batch_size,
            global_batch_size=args.global_batch_size,
            seq_length=args.encoder_seq_length,
            save=args.save, save_interval=args.save_interval,
            log_interval=args.log_interval, seed=args.seed,
        ),
    ).validate()


def t5_loss_fn(cfg, params, mb, rng, deterministic):
    return encdec.t5_loss(cfg.model, params, mb, rng, deterministic)


def main(argv=None):
    args = get_args(argv)
    sentinel_ids = None
    if args.vocab_size is not None:
        # tokenizer-less fallback: pad==bos==0, eos=1, sentinels = top
        # vocab ids (T5's extra_ids layout for a freshly built vocab)
        special = T5SpecialTokens(bos=0, eos=1, pad=0)
    else:
        if args.tokenizer_model is None:
            raise SystemExit("pass --tokenizer_model or --vocab_size")
        from megatron_llm_tpu.tokenizer.tokenizer import build_tokenizer

        tok = build_tokenizer("huggingface", args.tokenizer_model)
        inner = tok.inner
        args.vocab_size = tok.vocab_size
        pad = inner.pad_token_id if inner.pad_token_id is not None else 0
        special = T5SpecialTokens(
            bos=pad,  # T5 decoder starts with the pad token
            eos=inner.eos_token_id, pad=pad)
        extra = [inner.convert_tokens_to_ids(t)
                 for t in getattr(inner, "additional_special_tokens", [])]
        sentinel_ids = [i for i in extra if i is not None] or None
    cfg = t5_runtime_config(args)
    ds = T5Dataset(
        MMapIndexedDataset(args.data_path),
        args.encoder_seq_length, args.decoder_seq_length,
        cfg.model.vocab_size, special,
        masked_lm_prob=args.masked_lm_prob, seed=args.seed,
        sentinel_ids=sentinel_ids)
    params = encdec.init_t5_params(jax.random.key(args.seed), cfg.model,
                                   tp=args.tensor_parallel)
    specs = (encdec.t5_param_specs(cfg.model, cfg.parallel)
             if (args.tensor_parallel > 1
                 or args.use_distributed_optimizer) else None)
    pipeline_loss_fn = None
    if args.pipeline_parallel > 1:
        from megatron_llm_tpu.parallel import pipeline_encdec as pe

        params = pe.t5_to_pipeline_params(params, cfg.parallel)
        specs = pe.t5_pipeline_param_specs(cfg.model, cfg.parallel)
        pipeline_loss_fn = pe.t5_pipeline_loss
    return pretrain_custom(cfg, ds, params, t5_loss_fn, param_specs=specs,
                           pipeline_loss_fn=pipeline_loss_fn)


if __name__ == "__main__":
    main()
