"""Benchmark: training + serving throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

Headline anchor (BASELINE.md): the reference trains Llama-2-7B on 8× A100-80GB
at ≈890 tokens/s/GPU (bf16, flash-attn, sequence-parallel, selective
recompute) ⇒ model FLOPs utilization ≈ 0.12 of A100 bf16 peak (312 TFLOP/s)
counting 6·N·D + attention FLOPs with the reference's recompute settings.
A single v5e chip cannot hold 7B training state, so the bench trains a
Llama-architecture model sized to the chip and reports **MFU**, which is the
hardware-normalized apples-to-apples number; vs_baseline = our MFU / 0.12.

Besides the headline (seq 1024, the reference's finetune config), the JSON
carries: a seq-length MFU curve through 32k (BASELINE config 4's
long-context regime), a 7B-width training row, decode rows (bf16 via the
fused whole-stack Pallas decode kernel, int8, and 7B-width), prompt-lookup
speculative decoding rows on repetitive/random prompt mixes, and prefill
at both the decode point's 128-token prompts and an amortized 1024-token
prompt with its own MFU.

Process isolation (round 5): every point runs in a SUBPROCESS.  Round-5's
first in-process run had the 32k row's HBM footprint leak into every
subsequent point (ResourceExhausted on even the small decode jobs despite
del + clear_caches — intermittent; round 4 ran the same sequence clean).
A fresh backend per point makes the record insensitive to allocator state,
and a hung point (degraded tunnel) is killed by the parent's timeout
instead of sinking the whole record.

Measurement notes (v5e, 2026-07, don't re-derive):
- head_dim 128 beats 64 by +24% MFU (MXU lane width); mb=12 beats 8/16.
- Per-DISPATCH latency through the axon tunnel is ~0.8-1.1 ms: decode
  rates are only meaningful when the token loop runs on-device inside one
  executable (lax.while_loop / fori_loop) — timing per-step dispatches
  measures the tunnel, not the chip.
- Decode was op-chain-bound (~100us/layer vs 38us/layer read floor); the
  fused decode-step kernel (kernels/decode_step.py) removes the chain
  (93us/layer measured in-loop, 2.4x end-to-end).  Sibling-GEMV fusion
  measured 1.01x (XLA already overlaps independent matmuls) — dead end.
- The decode rate subtracts a separately-timed prefill; at a 128-token
  horizon the subtraction amplifies tunnel jitter ±40%, so the horizon is
  512 tokens (prefill correction ~few %).
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def _model_flops_per_token(cfg, seq_len: int) -> float:
    """6·N·D-style training FLOPs/token (fwd+bwd = 3× fwd) + attention."""
    h = cfg.hidden_size
    d = cfg.head_dim
    nq = cfg.num_attention_heads
    nkv = cfg.kv_heads
    ffn = cfg.ffn_size
    n_mlp = 3 if cfg.is_glu else 2
    per_layer_fwd = (
        2 * h * (nq * d) + 2 * 2 * h * (nkv * d) + 2 * (nq * d) * h
        + n_mlp * 2 * h * ffn
        + 2 * 2 * nq * d * seq_len  # scores + context, causal-halved ×2
    )
    fwd = cfg.num_layers * per_layer_fwd + 2 * h * cfg.padded_vocab_size()
    return 3.0 * fwd  # fwd + bwd


def chip_peak_flops(device_kind: str) -> float:
    """bf16 peak FLOP/s per chip for MFU normalization (also used by the
    tests_tpu MFU regression guard)."""
    peaks = {
        "v5 lite": 197e12, "v5e": 197e12,
        "v5p": 459e12, "v5": 459e12,
        "v4": 275e12, "v6e": 918e12, "v6 lite": 918e12,
    }
    kind = device_kind.lower().replace("tpu ", "")
    return next((v for k, v in peaks.items() if k in kind), 197e12)


def chip_hbm_bandwidth(device_kind: str) -> float:
    """HBM bytes/s per chip, for the decode bandwidth roofline."""
    bws = {
        "v5 lite": 819e9, "v5e": 819e9,
        "v5p": 2765e9, "v5": 2765e9,
        "v4": 1228e9, "v6e": 1640e9, "v6 lite": 1640e9,
    }
    kind = device_kind.lower().replace("tpu ", "")
    return next((v for k, v in bws.items() if k in kind), 819e9)


def _bench_model(seq: int, recompute: str):
    from megatron_llm_tpu.config import llama2_config

    # Llama-architecture model sized to one chip.  8 heads × d=128 (not
    # 16 × 64): the 128-wide head dim matches the MXU lane width and
    # measures ~24% faster at identical params/FLOPs.
    return llama2_config(
        "7b",
        hidden_size=1024,
        num_layers=24,
        num_attention_heads=8,
        num_kv_heads=8,
        ffn_hidden_size=2816,
        seq_length=seq,
        max_position_embeddings=seq,
        params_dtype="bfloat16",
        attention_impl="flash",
        recompute=recompute,
    )


def _bench_model_7b_width(seq: int, num_layers: int,
                          recompute: str = "selective"):
    """Llama-2-7B *width* (hidden 4096, ffn 11008, 32 q-heads × d128) at
    reduced depth so the state fits one chip; GQA (8 kv-heads) trims the
    kv projections the way the 34B/70B presets do.  MFU / decode rates at
    this width are the numbers comparable to the BASELINE 7B configs —
    per-layer matmul shapes are exactly the 7B ones, depth repeats them."""
    from megatron_llm_tpu.config import llama2_config

    return llama2_config(
        "7b",
        hidden_size=4096,
        num_layers=num_layers,
        num_attention_heads=32,
        num_kv_heads=8,
        ffn_hidden_size=11008,
        seq_length=seq,
        max_position_embeddings=seq,
        params_dtype="bfloat16",
        attention_impl="flash",
        recompute=recompute,
    )


def _train_point(seq: int, mb: int, recompute: str, iters: int, peak: float,
                 wide_layers: int = 0):
    """One training-throughput measurement → (tokens/sec, mfu, loss, n)."""
    import jax
    import jax.numpy as jnp

    from megatron_llm_tpu.config import (
        OptimizerConfig,
        ParallelConfig,
        RuntimeConfig,
        TrainConfig,
    )
    from megatron_llm_tpu.models import model as model_lib
    from megatron_llm_tpu.training.step import init_train_state, make_train_step

    model = (_bench_model_7b_width(seq, wide_layers, recompute)
             if wide_layers else _bench_model(seq, recompute))
    cfg = RuntimeConfig(
        model=model,
        parallel=ParallelConfig(),
        optimizer=OptimizerConfig(lr=1e-4, clip_grad=1.0),
        train=TrainConfig(train_iters=100, micro_batch_size=mb,
                          global_batch_size=mb, seq_length=seq),
    ).validate()

    params = model_lib.init_params(jax.random.key(0), cfg.model)
    n_params = sum(p.size for p in jax.tree.leaves(params))
    state = init_train_state(cfg, params)
    step = make_train_step(cfg)

    rng = np.random.default_rng(0)
    shape = (1, mb, seq)  # one microbatch per step
    tokens = rng.integers(0, cfg.model.vocab_size, shape)
    batch = {
        "tokens": jnp.asarray(tokens, jnp.int32),
        "labels": jnp.asarray(np.roll(tokens, -1, -1), jnp.int32),
        "loss_mask": jnp.ones(shape, jnp.float32),
    }
    key = jax.random.key(0)

    # warmup / compile — two steps: the first compiles, the second flushes
    # remaining lazy one-time work (allocator growth, executable warm-in)
    state, metrics = step(state, batch, key)
    float(metrics["loss"])
    state, metrics = step(state, batch, key)
    float(metrics["loss"])

    # Timing via an explicit host fetch of the last loss: the steps chain
    # through the donated state, so the fetch transitively waits for all.
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, batch, key)
    loss = float(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens_per_sec = iters * mb * seq / dt
    mfu = tokens_per_sec * _model_flops_per_token(cfg.model, seq) / peak
    return tokens_per_sec, mfu, loss, n_params


def _decode_roofline_tps(cfg, param_bytes: int, batch: int,
                         avg_cache_len: int, hbm_bw: float) -> float:
    """Bandwidth-bound decode tokens/s: each decode step must stream the
    weights once (shared across the batch; ``param_bytes`` = actual stored
    bytes, so int8 quantization moves the roofline) plus each sequence's
    KV cache; tokens/s = batch / (bytes_per_step / HBM_BW).  Compute and
    the int32 token traffic are negligible beside these two terms, so the
    bound is tight for small batches (the reference publishes no decode
    number; this roofline is the stated target per BASELINE.md)."""
    kv_elt_bytes = (1 + 4 / cfg.head_dim
                    if cfg.kv_cache_quant == "int8" else 2)
    kv_bytes = int(batch * 2 * cfg.num_layers * cfg.kv_heads
                   * cfg.head_dim * avg_cache_len * kv_elt_bytes)
    return batch / ((param_bytes + kv_bytes) / hbm_bw)


def _audited_decode_bytes(cfg, params, batch: int, avg_cache_len: int):
    """Per-step bytes a decode step actually streams → (weight_bytes,
    kv_bytes, by_class).  The naive roofline denominator (sum of every
    stored param byte + analytic KV bytes) overstates quantized decode
    traffic in one place: the word-embedding table.  Decode *gathers*
    ``batch`` rows of it per step — the full table only streams when it
    doubles as the unembedding matrix (tied embeddings).  Weight leaves
    are counted at stored width, so an int8 {q, scale} subtree
    contributes 1 byte/element + its scales and an int4 one ½ byte +
    group scales; KV bytes come from the cache's own per-position leaf
    sizes (exact {q, scale} traffic for int8 caches) rather than an
    analytic elt-size formula.

    ``by_class`` splits the weight term per tensor class — attn / mlp /
    embedding / norms / other — each as {"bytes", "precision"}, so a
    record shows *where* the decode bytes gap lives (round 9: with int8
    attn+MLP the embedding table and norms dominate the residual)."""
    import jax

    from megatron_llm_tpu.models import model as model_lib
    from megatron_llm_tpu.ops import quant

    def stored(leaf) -> int:
        if isinstance(leaf, dict):
            return sum(a.size * a.dtype.itemsize
                       for a in jax.tree.leaves(leaf))
        return leaf.size * leaf.dtype.itemsize

    def precision(leaf) -> str:
        if isinstance(leaf, dict):
            return f"int{quant.weight_bits(leaf)}"
        return str(leaf.dtype)

    by_class: dict = {}

    def tally(cls: str, nbytes: int, prec: str) -> None:
        row = by_class.setdefault(cls, {"bytes": 0, "precision": set()})
        row["bytes"] += int(nbytes)
        row["precision"].add(prec)

    flat, _ = jax.tree_util.tree_flatten_with_path(
        params, is_leaf=quant.is_quantized)
    weight_bytes = 0
    for path, leaf in flat:
        name = "/".join(str(getattr(k, "key", k)) for k in path)
        if "embedding" in name or "lm_head" in name:
            cls = "embedding"
        elif "norm" in name:
            cls = "norms"
        elif "attn" in name:
            cls = "attn"
        elif "mlp" in name:
            cls = "mlp"
        else:
            cls = "other"
        nbytes = stored(leaf)
        weight_bytes += nbytes
        tally(cls, nbytes, precision(leaf))

    word = params["embedding"]["word"]
    if not cfg.tie_embed_logits:
        stored_word = stored(word)
        if isinstance(word, dict):
            # int8-resident table: gather streams batch quantized rows
            # plus their per-row scales (ops/quant.py:embedding_lookup)
            gathered = batch * (
                word["q"].shape[-1] * word["q"].dtype.itemsize
                + word["scale"].dtype.itemsize)
        else:
            gathered = batch * word.shape[-1] * word.dtype.itemsize
        weight_bytes += gathered - stored_word
        by_class["embedding"]["bytes"] += gathered - stored_word
    for row in by_class.values():
        row["precision"] = "+".join(sorted(row["precision"]))
    # one cache position's stored bytes across all layers/heads/sides
    k1, v1 = model_lib.init_kv_cache(cfg, batch, 1)
    per_pos = sum(a.size * a.dtype.itemsize
                  for a in jax.tree.leaves((k1, v1)))
    return int(weight_bytes), int(per_pos * avg_cache_len), by_class


def _min_time(run, n=3):
    """Best-of-n wall time: tunnel latency drifts wildly between runs, and
    subtraction-based rates amplify single-shot jitter — minimums of
    repeated samples keep the record off the noise tails."""
    import jax

    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        jax.device_get(run())
        best = min(best, time.perf_counter() - t0)
    return best


def _decode_point(hbm_bw: float, quantize=False,
                  wide_layers: int = 0):
    """→ dict with decode tokens/sec, roofline tokens/sec, prefill
    tokens/sec.  ``quantize`` names a weight precision policy
    (ops/quant.py:POLICIES — "int8", "int4", "mixed"; ``True`` is
    accepted as "int8" for pre-v5 specs); any policy also puts the KV
    cache (ops/kv_quant.py) at int8, and every roofline term shrinks.
    With ``wide_layers`` the model is 7B-width at that depth (the fused
    decode kernel bows out on VMEM fit; the composed path serves)."""
    import jax
    import jax.numpy as jnp

    from megatron_llm_tpu.models import model as model_lib
    from megatron_llm_tpu.generation.generation import generate_tokens

    if quantize is True:
        quantize = "int8"

    # gen 512 (not 128): the decode rate is derived by subtracting a
    # separately-timed prefill from the full-generate window; at 512
    # steps the prefill correction is a few percent (see module notes).
    b, prompt_len, gen_len = 8, 128, 512
    cfg = (_bench_model_7b_width(prompt_len + gen_len, wide_layers)
           if wide_layers else _bench_model(prompt_len + gen_len,
                                            "selective"))
    if quantize:
        import dataclasses

        cfg = dataclasses.replace(cfg, kv_cache_quant="int8").validate()
    params = model_lib.init_params(jax.random.key(0), cfg)
    if quantize:
        from megatron_llm_tpu.ops.quant import (quantize_params,
                                                resolve_policy)

        params = quantize_params(params, resolve_policy(quantize))

    rng = np.random.default_rng(1)
    tokens = np.zeros((b, prompt_len + gen_len), np.int32)
    tokens[:, :prompt_len] = rng.integers(1, cfg.vocab_size,
                                          (b, prompt_len))
    tokens = jnp.asarray(tokens)
    lengths = jnp.full((b,), prompt_len, jnp.int32)

    out = generate_tokens(cfg, params, tokens, lengths,
                          use_eos_stop=False)  # warmup/compile
    jax.device_get(out.tokens)
    dt_full = _min_time(lambda: generate_tokens(
        cfg, params, tokens, lengths, use_eos_stop=False).tokens)

    # The roofline models per-step decode streaming only, so subtract the
    # prefill forward (the same [b, prompt_len] cached forward the
    # generate loop runs before its first decode step).
    rope = model_lib.rope_tables(cfg)

    @jax.jit
    def prefill(p, toks):
        k, v = model_lib.init_kv_cache(cfg, b, prompt_len + gen_len)
        logits, k, v = model_lib.forward_cached(
            cfg, p, toks, k, v, jnp.int32(0), rope=rope, empty_cache=True,
            last_logit_only=True)
        return logits[:, -1]

    jax.device_get(prefill(params, tokens[:, :prompt_len]))  # compile
    dt_prefill = _min_time(lambda: prefill(params, tokens[:, :prompt_len]))

    dt = max(dt_full - dt_prefill, 1e-9)
    tps = b * gen_len / dt
    prefill_tps = b * prompt_len / max(dt_prefill, 1e-9)
    param_bytes = sum(p.size * p.dtype.itemsize
                      for p in jax.tree.leaves(params))
    roof = _decode_roofline_tps(cfg, param_bytes, b,
                                prompt_len + gen_len // 2, hbm_bw)
    n_params = sum(p.size for p in jax.tree.leaves(params))
    result = {
        "tokens_per_sec": round(tps, 1),
        "roofline_tokens_per_sec": round(roof, 1),
        "roofline_frac": round(tps / roof, 4),
        "prefill_tokens_per_sec": round(prefill_tps, 1),
        "model_params": n_params,
    }
    if quantize:
        # per-step bytes-moved audit for the quantized points: the naive
        # denominator streams the (untied, gathered-not-streamed) word
        # embedding table every step, understating roofline_frac; the
        # audited denominator counts actual {q, scale} traffic
        # (docs/inference.md files the residual gap as a measured number)
        weight_bytes, kv_bytes, by_class = _audited_decode_bytes(
            cfg, params, b, prompt_len + gen_len // 2)
        roof_a = b * hbm_bw / (weight_bytes + kv_bytes)
        result.update({
            "step_weight_bytes": weight_bytes,
            "step_kv_bytes": kv_bytes,
            "step_bytes_by_class": by_class,
            "naive_roofline_frac": result["roofline_frac"],
            "roofline_tokens_per_sec": round(roof_a, 1),
            "roofline_frac": round(tps / roof_a, 4),
        })
    return result


def _pld_point(wide_layers: int = 0):
    """Prompt-lookup speculative decoding → dict of tokens/verify-forward,
    effective tok/s and full-window speedup vs the plain greedy loop, on a
    repetitive prompt mix (n-gram lookup can hit) and an incompressible
    random mix (it can't — measures graceful degradation).  All greedy,
    512-token horizon.

    Two rows ride in the record: the 374M bench model (random-init
    acceptance is measurable there: ~1.4-1.9 tokens/verify) and 7B width
    (acceptance on a RANDOM-INIT model is ~1.0 — its greedy continuation
    of a repeated motif does not repeat — so that row evidences graceful
    degradation: speedup ~0.998, i.e. the verify overhead is free).  Note
    the fused decode-step kernel now accelerates the 374M plain loop past
    PLD's composed-path verifies (measured 0.89x/0.69x); a fused
    multi-token verify step would recompose them (noted future work,
    kernels/decode_step.py)."""
    import jax
    import jax.numpy as jnp

    from megatron_llm_tpu.models import model as model_lib
    from megatron_llm_tpu.generation.generation import generate_tokens
    from megatron_llm_tpu.generation.speculative import generate_tokens_pld

    b, prompt_len, gen_len = 8, 128, 512
    cfg = (_bench_model_7b_width(prompt_len + gen_len, wide_layers)
           if wide_layers else _bench_model(prompt_len + gen_len,
                                            "selective"))
    params = model_lib.init_params(jax.random.key(0), cfg)
    rng = np.random.default_rng(2)

    def make_tokens(repetitive: bool):
        tokens = np.zeros((b, prompt_len + gen_len), np.int32)
        if repetitive:
            motif = rng.integers(1, cfg.vocab_size, (b, 16))
            tokens[:, :prompt_len] = np.tile(motif, (1, prompt_len // 16))
        else:
            tokens[:, :prompt_len] = rng.integers(1, cfg.vocab_size,
                                                  (b, prompt_len))
        return jnp.asarray(tokens), jnp.full((b,), prompt_len, jnp.int32)

    result = {"pld_model_width": cfg.hidden_size,
              "pld_model_layers": cfg.num_layers}
    for name, repetitive in (("repetitive", True), ("random", False)):
        tokens, lengths = make_tokens(repetitive)
        out = generate_tokens_pld(cfg, params, tokens, lengths,
                                  use_eos_stop=False)
        steps = float(np.max(np.asarray(out.steps)))
        dt_pld = _min_time(lambda: generate_tokens_pld(
            cfg, params, tokens, lengths, use_eos_stop=False).tokens, n=2)
        out2 = generate_tokens(cfg, params, tokens, lengths,
                               use_eos_stop=False)
        jax.device_get(out2.tokens)
        dt_plain = _min_time(lambda: generate_tokens(
            cfg, params, tokens, lengths, use_eos_stop=False).tokens, n=2)
        result[f"pld_tokens_per_verify_{name}"] = round(gen_len / steps, 2)
        result[f"pld_tokens_per_sec_{name}"] = round(b * gen_len / dt_pld, 1)
        result[f"pld_speedup_{name}"] = round(dt_plain / dt_pld, 3)
    return result


def _prefill_point(peak: float):
    """Amortized prefill: one cached forward over 1024-token prompts
    (b=8) → tokens/sec + prefill MFU.  The decode point's 128-token
    prompt prefill is latency-dominated through the tunnel; this is the
    capability number (VERDICT r4 weak #4)."""
    import jax
    import jax.numpy as jnp

    from megatron_llm_tpu.models import model as model_lib

    b, prompt_len = 8, 1024
    cfg = _bench_model(prompt_len + 128, "selective")
    params = model_lib.init_params(jax.random.key(0), cfg)
    rope = model_lib.rope_tables(cfg)
    rng = np.random.default_rng(3)
    toks = jnp.asarray(rng.integers(1, cfg.vocab_size, (b, prompt_len)),
                       jnp.int32)

    @jax.jit
    def prefill(p, toks):
        k, v = model_lib.init_kv_cache(cfg, b, prompt_len + 128)
        logits, k, v = model_lib.forward_cached(
            cfg, p, toks, k, v, jnp.int32(0), rope=rope, empty_cache=True,
            last_logit_only=True)
        return logits[:, -1]

    jax.device_get(prefill(params, toks))  # compile
    dt = _min_time(lambda: prefill(params, toks), n=5)
    tps = b * prompt_len / dt
    fwd_flops = _model_flops_per_token(cfg, prompt_len) / 3.0
    return {
        "prefill_long_tokens_per_sec": round(tps, 1),
        "prefill_long_mfu": round(tps * fwd_flops / peak, 4),
    }


def _serving_point():
    """Continuous-batching serving throughput (megatron_llm_tpu/serving/):
    24 concurrent requests over 8 KV slots → requests/s, aggregate decode
    tokens/s, mean/p95 per-token latency, TTFT, and the max per-iteration
    decode batch.  Unlike the one-shot decode row (a single fixed batch in
    one jitted loop), this pays per-iteration host scheduling — the number
    a real traffic mix gets from the engine the REST server now runs."""
    import jax

    from megatron_llm_tpu.models import model as model_lib
    from megatron_llm_tpu.serving.bench import run_serving_bench

    prompt_len, gen_len = 128, 128
    cfg = _bench_model(prompt_len + gen_len, "selective")
    params = model_lib.init_params(jax.random.key(0), cfg)
    return run_serving_bench(cfg, params, num_requests=24,
                             prompt_len=prompt_len, gen_len=gen_len,
                             slots=8)


def _serving_mixed_point(quantize: bool = False):
    """Mixed-workload serving (megatron_llm_tpu/serving/bench.py): varied
    prompt lengths with the long prompts arriving mid-decode, chunked
    prefill + pipelined decode on → aggregate tok/s, TTFT and ITL
    p50/p99, and the device/host step breakdown (device_idle_frac ~0 is
    the pipelining evidence).  This is the point where chunked prefill's
    ITL effect is visible: without it every long admission freezes the
    active streams for a whole-prompt prefill.

    With ``quantize`` the model serves fully int8-resident (int8 weights
    + int8 KV), the configuration the fused decode kernel's int8 path
    targets — the engine's fused_steps counter tells whether the slot
    batch actually took it.

    The plain (non-int8) point also reruns the identical workload with
    the span recorder off (trace=False) and stamps the untraced ITL
    percentiles into the same dict — the traced/untraced pair feeds the
    --compare tracing-overhead gate (docs/observability.md)."""
    import jax

    from megatron_llm_tpu.models import model as model_lib
    from megatron_llm_tpu.serving.bench import run_mixed_serving_bench

    max_prompt_len, gen_len = 256, 64
    cfg = _bench_model(max_prompt_len + gen_len, "selective")
    if quantize:
        import dataclasses

        cfg = dataclasses.replace(cfg, kv_cache_quant="int8").validate()
    params = model_lib.init_params(jax.random.key(0), cfg)
    if quantize:
        from megatron_llm_tpu.ops.quant import quantize_params

        params = quantize_params(params)
    out = run_mixed_serving_bench(cfg, params, num_requests=24,
                                  gen_len=gen_len, slots=8,
                                  max_prompt_len=max_prompt_len,
                                  prefill_chunk=64)
    if not quantize:
        # same workload, recorder off; jit caches are warm from the
        # traced run so this pays only its measurement window
        bare = run_mixed_serving_bench(cfg, params, num_requests=24,
                                       gen_len=gen_len, slots=8,
                                       max_prompt_len=max_prompt_len,
                                       prefill_chunk=64, trace=False)
        out["serving_mixed_itl_ms_p50_untraced"] = \
            bare["serving_mixed_itl_ms_p50"]
        out["serving_mixed_itl_ms_p99_untraced"] = \
            bare["serving_mixed_itl_ms_p99"]
    return out


def _serving_prefix_point():
    """Prefix-cache serving point (serving/prefix_cache.py): a wave of
    requests sharing one 896-token system prompt (64-token blocks) vs a
    wave with distinct prefixes, each request timed submit -> first
    token.  Headline ``serving_prefix_ttft_speedup`` = cold TTFT p50 /
    hit TTFT p50 — the acceptance bar is ≥ 3x at this geometry (the hit
    path runs one fused cache-assembly dispatch plus a 64-token bucket
    prefill instead of 928 prompt rows) — plus the hit rate; both gate
    in --compare."""
    import jax

    from megatron_llm_tpu.models import model as model_lib
    from megatron_llm_tpu.serving.bench import run_prefix_serving_bench

    shared_len, unique_len, gen_len = 896, 32, 16
    cfg = _bench_model(shared_len + unique_len + gen_len + 64, "selective")
    params = model_lib.init_params(jax.random.key(0), cfg)
    return run_prefix_serving_bench(
        cfg, params, num_requests=16, shared_len=shared_len,
        unique_len=unique_len, gen_len=gen_len, slots=8, block=64)


def _serving_paged_point():
    """Paged-KV serving point (serving/block_pool.py): mixed
    32/512/4096-token traffic at a FIXED HBM pool budget, paged 64-token
    blocks vs the fixed-stride baseline (``kv_block_size = max_seq_len``,
    the pre-paging one-row-per-slot layout) at the same pool bytes.
    Fixed stride pins a full max-length row per request whatever its real
    length, capping concurrency at the pool's whole-sequence count;
    paging allocates per 64 tokens of actual fill.  Headline
    ``serving_paged_max_concurrency`` gates in --compare; the acceptance
    bar is ≥ 2x the fixed-stride concurrency at this geometry, with paged
    ITL p50 riding along for the latency story."""
    import jax

    from megatron_llm_tpu.models import model as model_lib
    from megatron_llm_tpu.serving.bench import run_paged_serving_bench

    gen_len = 64
    cfg = _bench_model(4096 + gen_len, "selective")
    params = model_lib.init_params(jax.random.key(0), cfg)
    return run_paged_serving_bench(
        cfg, params, num_requests=12, prompt_lens=(32, 512, 4096),
        gen_len=gen_len, kv_block_size=64, pool_seqs=4)


def _serving_spec_point():
    """Speculative-decoding serving point (serving/engine.py spec path):
    repetitive traffic (tiled 8-token motifs, the workload prompt-lookup
    drafting exists for) spec on vs off at identical engine geometry,
    plus an incompressible random-traffic control where the acceptance
    EWMA must back the batch off to the plain pipelined path.  Headline
    ``serving_spec_itl_speedup`` = off ITL p50 / on ITL p50 gates in
    --compare (acceptance bar ≥ 1.3x at this geometry), with the
    acceptance rate riding along; ``serving_spec_random_overhead`` is
    the enabled-but-useless cost and must stay ≤ 1.05."""
    import jax

    from megatron_llm_tpu.models import model as model_lib
    from megatron_llm_tpu.serving.bench import run_spec_serving_bench

    prompt_len, gen_len = 256, 128
    cfg = _bench_model(prompt_len + gen_len, "selective")
    params = model_lib.init_params(jax.random.key(0), cfg)
    return run_spec_serving_bench(
        cfg, params, num_requests=16, prompt_len=prompt_len,
        gen_len=gen_len, slots=8, draft_len=4, ngram=3)


def _serving_spec_tree_point(wide_layers: int = 0):
    """Resident-draft tree-speculation serving point (serving/engine.py
    draft path, docs/serving.md "Tree speculation & resident drafts"):
    draft on vs off at identical engine geometry on random AND
    repetitive traffic.  Random traffic is the headline — it is exactly
    where the n-gram drafter's acceptance is ~0 (the PLD ceiling), so
    ``serving_spec_tree_itl_speedup`` (draft-off ITL p50 / draft-on, on
    random prompts) gating in --compare is the beat-the-ceiling claim
    (acceptance bar > 1.0).  Runs at 7B width (hidden 4096, L8 depth,
    the decode_7b geometry) when ``wide_layers`` is set so the headline
    is quoted at deployment-relevant matmul shapes; the bench draft is
    the perfect-oracle self-draft (a random-init target has no
    distilled partner — see serving/bench.py)."""
    import jax

    from megatron_llm_tpu.models import model as model_lib
    from megatron_llm_tpu.serving.bench import run_spec_tree_serving_bench

    prompt_len, gen_len = 256, 128
    cfg = (_bench_model_7b_width(prompt_len + gen_len, wide_layers)
           if wide_layers else _bench_model(prompt_len + gen_len,
                                            "selective"))
    params = model_lib.init_params(jax.random.key(0), cfg)
    return run_spec_tree_serving_bench(
        cfg, params, num_requests=16, prompt_len=prompt_len,
        gen_len=gen_len, slots=8, draft_len=4)


def _serving_cluster_point():
    """Multi-chip serving point (serving/cluster/, docs/serving.md
    "Multi-chip serving"): mixed traffic through ``build_cluster`` at 1
    vs 2 engine replicas on disjoint device slices, plus per-device
    resident param bytes at tp=1 vs tp=2 under the serving re-layout.
    Headlines ``serving_cluster_qps_ratio`` (acceptance bar ≥ 1.8x at 2
    replicas on real multi-chip hardware; on the CPU device-count
    simulation all "devices" share the host cores, so the simulated
    ratio only tracks plumbing cost) and
    ``serving_cluster_tp_model_size_ratio`` (≈ 2.0: a 2x larger model
    per chip) gate in --compare."""
    import jax

    from megatron_llm_tpu.models import model as model_lib
    from megatron_llm_tpu.serving.bench import run_cluster_serving_bench

    n_dev = len(jax.devices())
    if n_dev < 2:
        return {"serving_cluster_skipped":
                f"needs >= 2 devices, have {n_dev}"}
    gen_len, max_prompt_len = 32, 128
    cfg = _bench_model(max_prompt_len + gen_len, "selective")
    params = model_lib.init_params(jax.random.key(0), cfg)
    return run_cluster_serving_bench(
        cfg, params, num_requests=16, gen_len=gen_len, slots=4,
        max_prompt_len=max_prompt_len, replicas=2, tp=2)


def _serving_pp_point():
    """Pipeline-parallel serving point (docs/serving.md
    "Pipeline-parallel decode"): pp=2 as a real serving axis vs tp=2 at
    EQUAL device count.  Headlines ``serving_pp_param_bytes_ratio``
    (≈ 2.0: the layer-sharded layout halves per-device resident param
    bytes, so a 2x larger model fits the same per-chip HBM) in
    --compare; the ITL-vs-tp pair and the bitwise flag ride along.  As
    with serving_cluster, the CPU device-count simulation shares host
    cores across "devices", so only the residency ratio is a hardware-
    faithful claim in simulated runs."""
    import jax

    from megatron_llm_tpu.models import model as model_lib
    from megatron_llm_tpu.serving.bench import run_pp_serving_bench

    n_dev = len(jax.devices())
    if n_dev < 2:
        return {"serving_pp_skipped":
                f"needs >= 2 devices, have {n_dev}"}
    gen_len, max_prompt_len = 32, 128
    cfg = _bench_model(max_prompt_len + gen_len, "selective")
    params = model_lib.init_params(jax.random.key(0), cfg)
    return run_pp_serving_bench(
        cfg, params, num_requests=16, gen_len=gen_len, slots=4,
        max_prompt_len=max_prompt_len, pp=2)


def _serving_disagg_point(platform: str):
    """Disaggregated prefill/decode point (serving/cluster/,
    docs/serving.md "Disaggregated prefill/decode"): long-prompt traffic
    through ``build_disagg_cluster`` (1 prefill + 1 decode replica) vs
    ``build_cluster`` (2 colocated replicas) at EQUAL device count, plus
    a prefill-chunk MFU sweep on a single engine.  Headlines
    ``serving_disagg_ttft_p99_ratio`` (colocated TTFT p99 / disagg TTFT
    p99 — above 1 means shipping KV blocks out of a dedicated prefill
    engine beats interleaving admissions with decode),
    ``serving_disagg_qps_ratio``, and ``serving_disagg_prefill_mfu``
    (acceptance bar > 0.174 — above the training headline — on real
    hardware) gate in --compare.  As with serving_cluster, the CPU
    device-count simulation shares the host cores across "devices", so
    simulated ratios and MFU only track plumbing cost, not the claims."""
    import jax

    from megatron_llm_tpu.models import model as model_lib
    from megatron_llm_tpu.serving.bench import run_disagg_serving_bench

    n_dev = len(jax.devices())
    if n_dev < 2:
        return {"serving_disagg_skipped":
                f"needs >= 2 devices, have {n_dev}"}
    prompt_len, gen_len = 512, 32
    cfg = _bench_model(prompt_len + gen_len, "selective")
    params = model_lib.init_params(jax.random.key(0), cfg)
    return run_disagg_serving_bench(
        cfg, params, num_requests=16, gen_len=gen_len, slots=4,
        prompt_len=prompt_len, prefill_chunk=64,
        chunk_sweep=(64, 128, 256, 512),
        peak_flops=chip_peak_flops(platform))


def _serving_lora_point():
    """Multi-tenant LoRA serving point (serving/adapters/, docs/serving.md
    "Multi-tenant LoRA & live weight swap"): adapter-decorated traffic vs
    the same traffic on an adapter-less engine at identical geometry,
    plus a tenant-rotation wave through the LRU slot arena.  Gates:
    ``serving_lora_itl_overhead`` — resident-adapter ITL p50 over the
    base engine's — must stay ≤ 10% (lora_overhead_check; the price of
    the always-compiled grouped epilogue), and
    ``serving_lora_cache_hit_rate`` (repeat-pair tenant arrivals hitting
    the pinned arena slot) gates in --compare."""
    import jax

    from megatron_llm_tpu.models import model as model_lib
    from megatron_llm_tpu.serving.bench import run_lora_serving_bench

    prompt_len, gen_len = 128, 64
    cfg = _bench_model(prompt_len + gen_len, "selective")
    params = model_lib.init_params(jax.random.key(0), cfg)
    return run_lora_serving_bench(
        cfg, params, num_requests=16, prompt_len=prompt_len,
        gen_len=gen_len, slots=8, n_adapters=8, cache_slots=4, rank=8)


def _serving_tiered_point():
    """Tiered-KV serving point (serving/block_pool.py:HostKVTier,
    docs/serving.md "Tiered KV"): mixed-QoS traffic — low-priority batch
    decodes whose worst-case reservation covers the whole (deliberately
    small) device pool, plus high-priority interactive arrivals — with a
    host-RAM tier vs the queue-head-parking baseline at identical
    geometry.  Gates: ``serving_tiered_qps_ratio`` — interactive-class
    sustained QPS, tiered over parking (acceptance ≥ 1.5x: preemption
    serves the interactive class immediately instead of wedging it
    behind a batch decode) — and the interactive ITL p50 pair feeding
    tiered_overhead_check (swap pumping may cost ≤ 5% ITL p50)."""
    import jax

    from megatron_llm_tpu.models import model as model_lib
    from megatron_llm_tpu.serving.bench import run_tiered_serving_bench

    batch_prompt_len, batch_gen_len = 64, 128
    cfg = _bench_model(batch_prompt_len + batch_gen_len, "selective")
    params = model_lib.init_params(jax.random.key(0), cfg)
    return run_tiered_serving_bench(
        cfg, params, num_interactive=10, num_batch=2,
        interactive_prompt_len=32, interactive_gen_len=16,
        batch_prompt_len=batch_prompt_len, batch_gen_len=batch_gen_len,
        kv_block_size=32, slots=4)


def _transient_error_types():
    """The error classes worth retrying: the axon-tunneled compile service
    occasionally throws a transient remote-compile XlaRuntimeError.
    Deterministic bugs (NameError, TypeError, ...) must NOT be retried."""
    import jax

    types = [jax.errors.JaxRuntimeError]
    try:
        from jax._src.lib import _jax

        types.append(_jax.XlaRuntimeError)
    except Exception:  # noqa: BLE001 — internal layout varies by version
        pass
    return tuple(types)


def _retry(fn, *args, **kw):
    """One retry, transient (XLA runtime / remote-compile) errors only."""
    try:
        return fn(*args, **kw)
    except _transient_error_types() as e:
        print(f"# bench point failed ({type(e).__name__}); retrying once",
              flush=True)
        import jax

        jax.clear_caches()
        time.sleep(5)
        return fn(*args, **kw)


# ---------------------------------------------------------------------------
# Regression compare (--compare PREV.json [CURRENT.json])
# ---------------------------------------------------------------------------

# Metrics whose >10% regression fails CI (exit nonzero).  "mfu" is the
# record's "value" field (surfaced under its real name by _flatten_metrics).
_HEADLINE_METRICS = ("mfu", "decode_tokens_per_sec",
                     "decode_int8_roofline_frac",
                     # round 9 decode-bytes-gap points: int4 weight
                     # residency and the mixed (int8 attn / int4 MLP)
                     # policy must keep beating the int8 audited roofline
                     "decode_int4_roofline_frac",
                     "decode_mixed_roofline_frac",
                     "serving_prefix.serving_prefix_ttft_speedup",
                     "serving_prefix.serving_prefix_hit_rate",
                     "serving_paged.serving_paged_max_concurrency",
                     "serving_spec.serving_spec_itl_speedup",
                     "serving_spec.serving_spec_acceptance_rate",
                     # resident-draft tree speculation: the random-
                     # traffic ITL speedup (> 1.0 = beating the n-gram
                     # drafter's ceiling) with acceptance riding along
                     "serving_spec_tree.serving_spec_tree_itl_speedup",
                     "serving_spec_tree.serving_spec_tree_acceptance_rate",
                     # multi-chip serving: replica QPS scaling (≥ 1.8x at
                     # 2 replicas on real hardware) and the tp=2 per-chip
                     # model-size win (≈ 2.0)
                     "serving_cluster.serving_cluster_qps_ratio",
                     "serving_cluster.serving_cluster_tp_model_size_ratio",
                     # same ≈ tp gate over the mixed-precision tree
                     # (quantized subtrees + int8 embedding must shard)
                     "serving_cluster."
                     "serving_cluster_tp_quant_model_size_ratio",
                     # disaggregated prefill/decode vs colocated at equal
                     # device count: TTFT tail + QPS must not regress,
                     # and the prefill-chunk sweep's best MFU (> 0.174
                     # bar on real hardware) is the prefill-engine claim
                     "serving_disagg.serving_disagg_ttft_p99_ratio",
                     "serving_disagg.serving_disagg_qps_ratio",
                     "serving_disagg.serving_disagg_prefill_mfu",
                     # multi-tenant LoRA: repeat-pair tenant arrivals must
                     # keep hitting the pinned arena slot (a drop means
                     # admission stopped reusing residency); the ITL
                     # overhead gate rides separately in
                     # lora_overhead_check because smaller is better there
                     "serving_lora.serving_lora_cache_hit_rate",
                     # tiered KV: interactive-class QPS with host-RAM
                     # preemption over the queue-head-parking baseline
                     # (≥ 1.5x acceptance); the swap-overhead ITL gate
                     # rides separately in tiered_overhead_check
                     "serving_tiered.serving_tiered_qps_ratio",
                     # pipeline-parallel serving: the layer-sharded
                     # layout's per-device param-bytes win at pp=2
                     # (≈ 2.0; KV pool shards the same way)
                     "serving_pp.serving_pp_param_bytes_ratio")
_REGRESSION_TOLERANCE = 0.10
# Tracing must stay effectively free on the serving hot path: the mixed
# point's ITL p50 with the span recorder on may exceed the untraced rerun
# riding in the same record by at most this fraction.
_TRACE_OVERHEAD_TOLERANCE = 0.10
# The grouped LoRA epilogue rides in the fused decode step whenever a
# registry is attached; serving_lora's resident-adapter ITL p50 may
# exceed the adapter-less engine's by at most this fraction.
_LORA_OVERHEAD_TOLERANCE = 0.10
# Demote copies pump through the scheduler host phase; serving_tiered's
# interactive ITL p50 with the host tier on may exceed the parking
# baseline's by at most this fraction.
_TIERED_OVERHEAD_TOLERANCE = 0.05

# Bumped when the record's shape changes (new points / renamed keys) so
# --compare across old records is interpretable.
# v3: + serving_spec point (speculative decoding ITL speedup + acceptance)
# v4: + serving_cluster point (replica QPS scaling + tp model-size ratio)
# v5: + decode int4/mixed points, per-tensor-class step-bytes breakdown,
#     decode specs carry a precision-policy string in "quantize"
# v6: + serving_disagg point (disaggregated prefill/decode TTFT/QPS vs
#     colocated at equal devices + prefill-chunk MFU sweep)
# v7: + serving_spec_tree point (resident-draft tree speculation: random-
#     traffic ITL speedup vs draft-off + acceptance; the n-gram
#     serving_spec point rides unchanged for the PLD baseline)
# v8: + serving_lora point (multi-tenant LoRA: resident-adapter ITL vs
#     adapter-less base engine + LRU arena hit rate under tenant
#     rotation)
# v9: + serving_tiered point (tiered KV: interactive-class QPS with
#     host-RAM preemption vs queue-head parking + the swap-overhead ITL
#     pair)
# v10: + serving_pp point (pipeline-parallel decode: per-device param-
#      bytes ratio at pp=2 / fsdp=2 vs single-mesh, ITL vs tp=2 at
#      equal devices, bitwise flag)
_BENCH_SCHEMA_VERSION = 10


def _run_metadata(platform: str, device_count: int) -> dict:
    """Provenance stamped into the record as ``run_meta``: without the
    git sha + jax version + device geometry, two BENCH_*.json files a few
    rounds apart cannot be attributed to code vs toolchain vs topology."""
    import os
    import subprocess

    meta = {
        "schema_version": _BENCH_SCHEMA_VERSION,
        "device_kind": platform,
        "device_count": device_count,
    }
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        sha = subprocess.run(
            ["git", "rev-parse", "--short=12", "HEAD"],
            capture_output=True, text=True, timeout=10, cwd=here)
        if sha.returncode == 0 and sha.stdout.strip():
            meta["git_sha"] = sha.stdout.strip()
            dirty = subprocess.run(
                ["git", "status", "--porcelain"],
                capture_output=True, text=True, timeout=10, cwd=here)
            if dirty.returncode == 0 and dirty.stdout.strip():
                meta["git_dirty"] = True
    except (OSError, subprocess.TimeoutExpired):
        pass  # not a git checkout / git missing: record stays attributable
    try:
        import importlib.metadata

        meta["jax_version"] = importlib.metadata.version("jax")
    except Exception:  # noqa: BLE001 — provenance only, never fatal
        pass
    return meta


def _flatten_metrics(record: dict, prefix: str = "") -> dict:
    """Numeric leaves of a BENCH record as a flat {dotted.name: float}.
    The headline "value" field is renamed "mfu"; lists (the mfu_vs_seq
    curve) are skipped — their rows move between runs — and so is
    run_meta (provenance, not a measurement; device_count deltas must
    not read as regressions)."""
    out = {}
    for key, val in record.items():
        name = f"{prefix}{key}"
        if key == "value" and not prefix:
            name = "mfu"
        if key == "run_meta" and not prefix:
            continue
        if isinstance(val, bool):
            continue
        if isinstance(val, (int, float)):
            out[name] = float(val)
        elif isinstance(val, dict):
            out.update(_flatten_metrics(val, prefix=f"{name}."))
    return out


def trace_overhead_check(record: dict):
    """→ (line, ok): the tracing-overhead gate.  The serving_mixed point
    records ITL p50 with the span recorder on AND off; tracing is only
    acceptable as an always-on default while the traced number stays
    within _TRACE_OVERHEAD_TOLERANCE of the untraced one (the --no_trace
    server flag is the escape hatch if this ever trips)."""
    sm = record.get("serving_mixed") or {}
    traced = sm.get("serving_mixed_itl_ms_p50")
    untraced = sm.get("serving_mixed_itl_ms_p50_untraced")
    if not traced or not untraced:
        return ("# trace-overhead gate: skipped "
                "(no traced/untraced ITL pair in record)"), True
    overhead = traced / untraced - 1.0
    ok = traced <= (1.0 + _TRACE_OVERHEAD_TOLERANCE) * untraced
    line = (f"# trace-overhead gate: serving_mixed_itl_ms_p50 {traced:g} "
            f"traced vs {untraced:g} untraced ({overhead:+.1%}, limit "
            f"+{_TRACE_OVERHEAD_TOLERANCE:.0%})"
            + ("" if ok else "  << REGRESSION"))
    return line, ok


def lora_overhead_check(record: dict):
    """→ (line, ok): the LoRA-epilogue-overhead gate.  The serving_lora
    point records resident-adapter ITL p50 against the adapter-less base
    engine's at identical geometry; attaching a registry is only
    acceptable as a serving default while the adapter-decorated number
    stays within _LORA_OVERHEAD_TOLERANCE of base (running without a
    registry — which keeps the pre-LoRA executable — is the escape
    hatch if this ever trips)."""
    sl = record.get("serving_lora") or {}
    lora = sl.get("serving_lora_itl_ms_p50")
    base = sl.get("serving_lora_base_itl_ms_p50")
    if not lora or not base:
        return ("# lora-overhead gate: skipped "
                "(no lora/base ITL pair in record)"), True
    overhead = lora / base - 1.0
    ok = lora <= (1.0 + _LORA_OVERHEAD_TOLERANCE) * base
    line = (f"# lora-overhead gate: serving_lora_itl_ms_p50 {lora:g} "
            f"with adapters vs {base:g} base ({overhead:+.1%}, limit "
            f"+{_LORA_OVERHEAD_TOLERANCE:.0%})"
            + ("" if ok else "  << REGRESSION"))
    return line, ok


def tiered_overhead_check(record: dict):
    """→ (line, ok): the tiered-KV swap-overhead gate.  The
    serving_tiered point records interactive ITL p50 with the host tier
    on against the parking baseline at identical geometry; keeping the
    tier on is only acceptable while pumping demote copies through the
    scheduler host phase costs at most _TIERED_OVERHEAD_TOLERANCE of
    interactive ITL p50 (``--host_kv_blocks 0`` — which removes the
    tier and the pump entirely — is the escape hatch if this trips)."""
    st = record.get("serving_tiered") or {}
    tiered = st.get("serving_tiered_itl_ms_p50")
    base = st.get("serving_tiered_parked_itl_ms_p50")
    if not tiered or not base:
        return ("# tiered-overhead gate: skipped "
                "(no tiered/parked ITL pair in record)"), True
    overhead = tiered / base - 1.0
    ok = tiered <= (1.0 + _TIERED_OVERHEAD_TOLERANCE) * base
    line = (f"# tiered-overhead gate: serving_tiered_itl_ms_p50 {tiered:g} "
            f"with host tier vs {base:g} parked ({overhead:+.1%}, limit "
            f"+{_TIERED_OVERHEAD_TOLERANCE:.0%})"
            + ("" if ok else "  << REGRESSION"))
    return line, ok


def compare_records(prev: dict, cur: dict):
    """Per-metric deltas between two BENCH records → (lines, regressed).

    ``lines`` is a human-readable report (one line per metric present in
    either record); ``regressed`` lists the headline metrics that dropped
    more than _REGRESSION_TOLERANCE — latency-style metrics are reported
    but never gate, because for every headline metric here bigger is
    better."""
    p, c = _flatten_metrics(prev), _flatten_metrics(cur)
    lines, regressed = [], []
    for name in sorted(set(p) | set(c)):
        if name not in p:
            lines.append(f"  {name}: (new) {c[name]:g}")
            continue
        if name not in c:
            lines.append(f"  {name}: {p[name]:g} -> MISSING")
            if name in _HEADLINE_METRICS:
                regressed.append(name)
            continue
        pv, cv = p[name], c[name]
        delta = (cv - pv) / abs(pv) if pv else 0.0
        mark = ""
        if name in _HEADLINE_METRICS and delta < -_REGRESSION_TOLERANCE:
            regressed.append(name)
            mark = "  << REGRESSION"
        lines.append(f"  {name}: {pv:g} -> {cv:g} ({delta:+.1%}){mark}")
    return lines, regressed


def _load_record(path: str) -> dict:
    """Last JSON-object line of a BENCH_*.json file (the bench prints
    '#'-prefixed progress lines before the record)."""
    record = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line.startswith("{"):
                record = json.loads(line)
    if record is None:
        raise ValueError(f"no JSON record line in {path}")
    return record


def _run_compare(prev_path: str, cur_record: dict) -> int:
    prev = _load_record(prev_path)
    for tag, rec in (("prev", prev), ("cur", cur_record)):
        meta = rec.get("run_meta")
        if meta:
            print(f"# {tag} run_meta: {json.dumps(meta, sort_keys=True)}",
                  flush=True)
    lines, regressed = compare_records(prev, cur_record)
    print(f"# compare vs {prev_path} "
          f"(gate: {', '.join(_HEADLINE_METRICS)} "
          f"> {_REGRESSION_TOLERANCE:.0%} drop):", flush=True)
    for line in lines:
        print("#" + line, flush=True)
    trace_line, trace_ok = trace_overhead_check(cur_record)
    print(trace_line, flush=True)
    lora_line, lora_ok = lora_overhead_check(cur_record)
    print(lora_line, flush=True)
    tiered_line, tiered_ok = tiered_overhead_check(cur_record)
    print(tiered_line, flush=True)
    if regressed or not trace_ok or not lora_ok or not tiered_ok:
        if regressed:
            print(f"# REGRESSED: {', '.join(regressed)}", flush=True)
        if not trace_ok:
            print("# REGRESSED: tracing overhead over limit", flush=True)
        if not lora_ok:
            print("# REGRESSED: LoRA epilogue overhead over limit",
                  flush=True)
        if not tiered_ok:
            print("# REGRESSED: tiered-KV swap overhead over limit",
                  flush=True)
        return 1
    print("# no headline regression", flush=True)
    return 0


# ---------------------------------------------------------------------------
# Orchestration: one subprocess per point (see module docstring)
# ---------------------------------------------------------------------------

_CHILD_MARK = "##BENCH_POINT##"


def _relay_progress(text: str) -> None:
    """Forward a child's '#'-prefixed progress lines to our stdout."""
    for line in text.splitlines():
        if line.startswith("#") and not line.startswith(_CHILD_MARK):
            print(line, flush=True)


def _child_main(spec_json: str) -> None:
    spec = json.loads(spec_json)
    platform = spec["platform"]
    peak = chip_peak_flops(platform)
    hbm_bw = chip_hbm_bandwidth(platform)
    kind = spec["kind"]
    if kind == "train":
        out = _retry(_train_point, spec["seq"], spec["mb"], spec["rc"],
                     spec["iters"], peak, spec.get("wide_layers", 0))
    elif kind == "decode":
        out = _retry(_decode_point, hbm_bw, spec.get("quantize", False),
                     spec.get("wide_layers", 0))
    elif kind == "pld":
        out = _retry(_pld_point, spec.get("wide_layers", 0))
    elif kind == "prefill":
        out = _retry(_prefill_point, peak)
    elif kind == "serving":
        out = _retry(_serving_point)
    elif kind == "serving_mixed":
        out = _retry(_serving_mixed_point, spec.get("quantize", False))
    elif kind == "serving_prefix":
        out = _retry(_serving_prefix_point)
    elif kind == "serving_paged":
        out = _retry(_serving_paged_point)
    elif kind == "serving_lora":
        out = _retry(_serving_lora_point)
    elif kind == "serving_tiered":
        out = _retry(_serving_tiered_point)
    elif kind == "serving_spec":
        out = _retry(_serving_spec_point)
    elif kind == "serving_spec_tree":
        out = _retry(_serving_spec_tree_point, spec.get("wide_layers", 0))
    elif kind == "serving_cluster":
        out = _retry(_serving_cluster_point)
    elif kind == "serving_pp":
        out = _retry(_serving_pp_point)
    elif kind == "serving_disagg":
        out = _retry(_serving_disagg_point, platform)
    else:  # pragma: no cover - parent and child ship together
        raise ValueError(f"unknown point kind {kind!r}")
    print(_CHILD_MARK + json.dumps(out), flush=True)


def _point(label: str, spec: dict, timeout_s: int = 900,
           env: dict | None = None):
    """Run one measurement in a fresh subprocess → parsed result or None.

    Isolation is the point: a crashed, hung, or HBM-leaking measurement
    cannot take the rest of the record down with it (round 2 lost the
    train curve to a late crash; round 5 lost decode rows to in-process
    HBM contamination)."""
    import os
    import subprocess

    t0 = time.perf_counter()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--point",
             json.dumps(spec)],
            capture_output=True, text=True, timeout=timeout_s,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            env=(None if env is None else {**os.environ, **env}))
    except subprocess.TimeoutExpired as e:
        # surface the child's progress lines so the hung stage (compile /
        # warmup / timed window) is identifiable without a rerun
        partial = e.stdout or b""
        if isinstance(partial, bytes):
            partial = partial.decode(errors="replace")
        _relay_progress(partial)
        print(f"# bench point {label} TIMED OUT after {timeout_s}s",
              flush=True)
        return None
    _relay_progress(proc.stdout or "")
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-1:] or ["?"]
        print(f"# bench point {label} FAILED (rc={proc.returncode}): "
              f"{tail[0]}", flush=True)
        return None
    for line in (proc.stdout or "").splitlines():
        if line.startswith(_CHILD_MARK):
            print(f"# bench point {label} ok "
                  f"({time.perf_counter() - t0:.0f}s)", flush=True)
            return json.loads(line[len(_CHILD_MARK):])
    print(f"# bench point {label} produced no result line", flush=True)
    return None


def _detect_device(timeout_s: int = 240):
    """First device's kind + visible device count, probed in a SUBPROCESS
    with a hard timeout.

    A degraded axon tunnel makes ``jax.devices()`` hang indefinitely
    *inside a C call* — a benchmark that hangs is worse for the driver
    than one that emits a structured failure record quickly."""
    import subprocess

    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; ds = jax.devices(); "
             "print(ds[0].device_kind); print(len(ds))"],
            capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        raise TimeoutError(
            f"device probe exceeded {timeout_s}s "
            "(accelerator tunnel unreachable?)")
    if out.returncode != 0:
        tail = (out.stderr or "").strip().splitlines()[-1:] or ["?"]
        raise RuntimeError(f"device probe failed: {tail[0]}")
    lines = (out.stdout or "").strip().splitlines()
    if not lines:
        raise RuntimeError("device probe printed nothing")
    if len(lines) >= 2 and lines[-1].isdigit():
        return lines[-2], int(lines[-1])
    return lines[-1], 1


def main() -> None:
    if len(sys.argv) >= 3 and sys.argv[1] == "--point":
        _child_main(sys.argv[2])
        return
    compare_prev = None
    if len(sys.argv) >= 2 and sys.argv[1] == "--compare":
        if len(sys.argv) >= 4:
            # file-vs-file mode: no measurement, pure CI gate
            raise SystemExit(_run_compare(sys.argv[2],
                                          _load_record(sys.argv[3])))
        if len(sys.argv) == 3:
            # run the bench, then gate the fresh record against PREV
            compare_prev = sys.argv[2]
        else:
            raise SystemExit("usage: bench.py --compare PREV.json "
                             "[CURRENT.json]")

    try:
        platform, device_count = _detect_device()
    except (TimeoutError, RuntimeError, OSError) as e:
        print(json.dumps({
            "metric": "mfu", "value": None, "unit": "fraction_of_peak",
            "vs_baseline": None,
            "error": f"{type(e).__name__}: {e}",
        }))
        raise SystemExit(1)

    def train_spec(seq, mb, rc, iters, wide_layers=0):
        return {"kind": "train", "platform": platform, "seq": seq,
                "mb": mb, "rc": rc, "iters": iters,
                "wide_layers": wide_layers}

    # Headline: seq 1024 (the reference's finetune config), measured
    # single-chip sweet spot mb=12, selective recompute; mb=8 fallback.
    headline = _point("train@1024", train_spec(1024, 12, "selective", 30))
    headline_config = "mb12"
    if headline is None:
        headline = _point("train@1024/fallback",
                          train_spec(1024, 8, "selective", 10))
        headline_config = "mb8-fallback"

    curve = []
    if headline is not None:
        tps, mfu, loss, n_params = headline
        curve.append({"seq_length": 1024, "mfu": round(mfu, 4),
                      "tokens_per_sec": round(tps, 1)})

    # MFU-vs-seq curve (BASELINE config 4 regime at 32k): selective remat
    # while it fits, full remat beyond 8k.
    for seq, mb, rc, iters in ((4096, 3, "selective", 10),
                               (8192, 1, "selective", 10),
                               (16384, 1, "full", 5),
                               (32768, 1, "full", 5)):
        p = _point(f"train@{seq}", train_spec(seq, mb, rc, iters))
        if p is not None:
            c_tps, c_mfu, _, _ = p
            curve.append({"seq_length": seq, "mfu": round(c_mfu, 4),
                          "tokens_per_sec": round(c_tps, 1)})

    # 7B-width training point.  Measured ladder on v5e (2026-07-31):
    # L3/mb2/selective 0.556, L2/mb2/selective 0.535, L3/mb1/full 0.441 —
    # mb ≥ 2 + selective remat is the lever.
    for layers, mb, rc in ((3, 2, "selective"), (2, 2, "selective"),
                           (2, 1, "full")):
        wide = _point(f"train@4096/7b-width-L{layers}",
                      train_spec(4096, mb, rc, 5, wide_layers=layers))
        if wide is not None:
            w_tps, w_mfu, _, w_params = wide
            curve.append({"seq_length": 4096, "mfu": round(w_mfu, 4),
                          "tokens_per_sec": round(w_tps, 1),
                          "config": f"7b-width-L{layers}-mb{mb}-{rc}",
                          "model_params": w_params})
            break

    decode = _point("decode", {"kind": "decode", "platform": platform})
    decode_q = _point("decode/int8", {"kind": "decode",
                                      "platform": platform,
                                      "quantize": "int8"})
    decode_i4 = _point("decode/int4", {"kind": "decode",
                                       "platform": platform,
                                       "quantize": "int4"})
    decode_mx = _point("decode/mixed", {"kind": "decode",
                                        "platform": platform,
                                        "quantize": "mixed"})
    decode_7b = _point("decode/7b-width-L8",
                       {"kind": "decode", "platform": platform,
                        "wide_layers": 8}, timeout_s=1200)
    pld = _point("decode/pld", {"kind": "pld", "platform": platform},
                 timeout_s=1200)
    pld_7b = _point("decode/pld-7b-width",
                    {"kind": "pld", "platform": platform,
                     "wide_layers": 8}, timeout_s=1200)
    prefill_long = _point("prefill@1024", {"kind": "prefill",
                                           "platform": platform})
    serving = _point("serving", {"kind": "serving", "platform": platform},
                     timeout_s=1200)
    serving_mixed = _point("serving/mixed",
                           {"kind": "serving_mixed", "platform": platform},
                           timeout_s=1200)
    serving_mixed_q = _point("serving/mixed-int8",
                             {"kind": "serving_mixed", "platform": platform,
                              "quantize": True},
                             timeout_s=1200)
    serving_prefix = _point("serving/prefix",
                            {"kind": "serving_prefix",
                             "platform": platform},
                            timeout_s=1200)
    serving_paged = _point("serving/paged",
                           {"kind": "serving_paged",
                            "platform": platform},
                           timeout_s=1800)
    serving_spec = _point("serving/spec",
                          {"kind": "serving_spec",
                           "platform": platform},
                          timeout_s=1800)
    serving_lora = _point("serving/lora",
                          {"kind": "serving_lora",
                           "platform": platform},
                          timeout_s=1800)
    serving_tiered = _point("serving/tiered",
                            {"kind": "serving_tiered",
                             "platform": platform},
                            timeout_s=1800)
    # headline quoted at 7B width (decode_7b geometry) so the
    # beat-the-PLD-ceiling claim holds at deployment matmul shapes; on
    # CPU the wide model would blow the point timeout, so the simulated
    # record carries the standard bench-model geometry instead
    serving_spec_tree = _point(
        "serving/spec-tree",
        {"kind": "serving_spec_tree", "platform": platform,
         "wide_layers": 0 if platform == "cpu" else 8},
        timeout_s=1800)
    # CPU runs simulate 8 devices so the replica/tp topology exercises
    # end to end; on real hardware the flag is inert (jax ignores the
    # host-platform count when an accelerator is present)
    cluster_env = None
    if platform == "cpu":
        import os as _os

        cluster_env = {"XLA_FLAGS": (
            _os.environ.get("XLA_FLAGS", "") +
            " --xla_force_host_platform_device_count=8").strip()}
    serving_cluster = _point("serving/cluster",
                             {"kind": "serving_cluster",
                              "platform": platform},
                             timeout_s=1800, env=cluster_env)
    serving_pp = _point("serving/pp",
                        {"kind": "serving_pp", "platform": platform},
                        timeout_s=1800, env=cluster_env)
    serving_disagg = _point("serving/disagg",
                            {"kind": "serving_disagg",
                             "platform": platform},
                            timeout_s=1800, env=cluster_env)

    baseline_mfu = 0.12  # reference 890 tok/s/GPU on A100 ⇒ ~0.12 MFU
    record = {
        "metric": "mfu",
        "value": None,
        "unit": "fraction_of_peak",
        "vs_baseline": None,
        "seq_length": 1024,
        "device": platform,
        "run_meta": _run_metadata(platform, device_count),
        "mfu_vs_seq": curve,
    }
    if decode is not None:
        record.update({
            "decode_tokens_per_sec": decode["tokens_per_sec"],
            "decode_roofline_tokens_per_sec":
                decode["roofline_tokens_per_sec"],
            "decode_roofline_frac": decode["roofline_frac"],
            "prefill_tokens_per_sec": decode["prefill_tokens_per_sec"],
        })
    for tag, dq in (("int8", decode_q), ("int4", decode_i4),
                    ("mixed", decode_mx)):
        if dq is None:
            continue
        record.update({
            f"decode_tokens_per_sec_{tag}": dq["tokens_per_sec"],
            f"decode_{tag}_roofline_frac": dq["roofline_frac"],
        })
        if "step_weight_bytes" in dq:
            # bytes-moved audit (definition change vs pre-audit records:
            # roofline_frac now uses the audited denominator; the naive
            # value rides along for continuity — docs/inference.md) plus
            # the v5 per-tensor-class breakdown showing where the
            # residual decode bytes live
            record.update({
                f"decode_{tag}_step_weight_bytes":
                    dq["step_weight_bytes"],
                f"decode_{tag}_step_kv_bytes": dq["step_kv_bytes"],
                f"decode_{tag}_step_bytes_by_class":
                    dq["step_bytes_by_class"],
                f"decode_{tag}_naive_roofline_frac":
                    dq["naive_roofline_frac"],
            })
    if decode_7b is not None:
        record["decode_7b_width"] = decode_7b
    if pld is not None:
        record.update(pld)
    if pld_7b is not None:
        record["pld_7b_width"] = pld_7b
    if prefill_long is not None:
        record.update(prefill_long)
    if serving is not None:
        record["serving"] = serving
    if serving_mixed is not None:
        record["serving_mixed"] = serving_mixed
    if serving_mixed_q is not None:
        record["serving_mixed_int8"] = serving_mixed_q
    if serving_prefix is not None:
        record["serving_prefix"] = serving_prefix
    if serving_paged is not None:
        record["serving_paged"] = serving_paged
    if serving_spec is not None:
        record["serving_spec"] = serving_spec
    if serving_lora is not None:
        record["serving_lora"] = serving_lora
    if serving_tiered is not None:
        record["serving_tiered"] = serving_tiered
    if serving_spec_tree is not None:
        record["serving_spec_tree"] = serving_spec_tree
    if serving_cluster is not None:
        record["serving_cluster"] = serving_cluster
    if serving_pp is not None:
        record["serving_pp"] = serving_pp
    if serving_disagg is not None:
        record["serving_disagg"] = serving_disagg
    if headline is not None:
        record.update({
            "value": round(mfu, 4),
            "vs_baseline": round(mfu / baseline_mfu, 3),
            "tokens_per_sec_per_chip": round(tps, 1),
            "model_params": n_params,
            "loss": loss,
            "headline_config": headline_config,
        })
    print(json.dumps(record))
    if compare_prev is not None:
        raise SystemExit(_run_compare(compare_prev, record))


if __name__ == "__main__":
    main()
