"""Benchmark: training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

Headline anchor (BASELINE.md): the reference trains Llama-2-7B on 8× A100-80GB
at ≈890 tokens/s/GPU (bf16, flash-attn, sequence-parallel, selective
recompute) ⇒ model FLOPs utilization ≈ 0.12 of A100 bf16 peak (312 TFLOP/s)
counting 6·N·D + attention FLOPs with the reference's recompute settings.
A single v5e chip cannot hold 7B training state, so the bench trains a
Llama-architecture model sized to the chip and reports **MFU**, which is the
hardware-normalized apples-to-apples number; vs_baseline = our MFU / 0.12.
"""

from __future__ import annotations

import json
import time

import numpy as np


def _model_flops_per_token(cfg, seq_len: int) -> float:
    """6·N·D-style training FLOPs/token (fwd+bwd = 3× fwd) + attention."""
    h = cfg.hidden_size
    d = cfg.head_dim
    nq = cfg.num_attention_heads
    nkv = cfg.kv_heads
    ffn = cfg.ffn_size
    n_mlp = 3 if cfg.is_glu else 2
    per_layer_fwd = (
        2 * h * (nq * d) + 2 * 2 * h * (nkv * d) + 2 * (nq * d) * h
        + n_mlp * 2 * h * ffn
        + 2 * 2 * nq * d * seq_len  # scores + context, causal-halved ×2
    )
    fwd = cfg.num_layers * per_layer_fwd + 2 * h * cfg.padded_vocab_size()
    return 3.0 * fwd  # fwd + bwd


def main() -> None:
    import jax
    import jax.numpy as jnp

    from megatron_llm_tpu.config import (
        OptimizerConfig,
        ParallelConfig,
        RuntimeConfig,
        TrainConfig,
        llama2_config,
    )
    from megatron_llm_tpu.models import model as model_lib
    from megatron_llm_tpu.training.step import init_train_state, make_train_step

    # seq 1024 matches the reference's headline finetune config (BASELINE.md:
    # Llama-2-7B at seq 1024); mb 8 is the measured single-chip sweet spot.
    seq = 1024
    mb = 8
    model = llama2_config(
        "7b",
        hidden_size=1024,
        num_layers=24,
        num_attention_heads=16,
        num_kv_heads=16,
        ffn_hidden_size=2816,
        seq_length=seq,
        max_position_embeddings=seq,
        params_dtype="bfloat16",
        # "flash" falls back to the einsum path until the Pallas kernel
        # lands; request it so the bench picks the kernel up automatically.
        attention_impl="flash",
        recompute="selective",
    )
    cfg = RuntimeConfig(
        model=model,
        parallel=ParallelConfig(),
        optimizer=OptimizerConfig(lr=1e-4, clip_grad=1.0),
        train=TrainConfig(train_iters=100, micro_batch_size=mb,
                          global_batch_size=mb, seq_length=seq),
    ).validate()

    params = model_lib.init_params(jax.random.key(0), cfg.model)
    n_params = sum(p.size for p in jax.tree.leaves(params))
    state = init_train_state(cfg, params)
    step = make_train_step(cfg)

    rng = np.random.default_rng(0)
    shape = (1, mb, seq)  # one microbatch per step
    tokens = rng.integers(0, cfg.model.vocab_size, shape)
    batch = {
        "tokens": jnp.asarray(tokens, jnp.int32),
        "labels": jnp.asarray(np.roll(tokens, -1, -1), jnp.int32),
        "loss_mask": jnp.ones(shape, jnp.float32),
    }
    key = jax.random.key(0)

    # warmup / compile
    state, metrics = step(state, batch, key)
    float(metrics["loss"])

    # Timing via an explicit host fetch of the last loss: the steps chain
    # through the donated state, so the fetch transitively waits for all of
    # them.  (block_until_ready proved unreliable for independent outputs
    # over the axon-tunneled backend; a host read is unambiguous.)
    iters = 20
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, batch, key)
    float(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens_per_sec = iters * mb * seq / dt
    flops_per_token = _model_flops_per_token(cfg.model, seq)
    achieved = tokens_per_sec * flops_per_token
    platform = jax.devices()[0].device_kind
    peaks = {  # bf16 peak FLOP/s per chip
        "v5 lite": 197e12, "v5e": 197e12,
        "v5p": 459e12, "v5": 459e12,
        "v4": 275e12, "v6e": 918e12, "v6 lite": 918e12,
    }
    kind = platform.lower().replace("tpu ", "")
    peak = next((v for k, v in peaks.items() if k in kind), 197e12)
    mfu = achieved / peak
    baseline_mfu = 0.12  # reference 890 tok/s/GPU on A100 ⇒ ~0.12 MFU

    print(json.dumps({
        "metric": "mfu",
        "value": round(mfu, 4),
        "unit": "fraction_of_peak",
        "vs_baseline": round(mfu / baseline_mfu, 3),
        "tokens_per_sec_per_chip": round(tokens_per_sec, 1),
        "model_params": n_params,
        "seq_length": seq,
        "device": platform,
        "loss": float(metrics["loss"]),
    }))


if __name__ == "__main__":
    main()
