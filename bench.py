"""Benchmark: training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

Headline anchor (BASELINE.md): the reference trains Llama-2-7B on 8× A100-80GB
at ≈890 tokens/s/GPU (bf16, flash-attn, sequence-parallel, selective
recompute) ⇒ model FLOPs utilization ≈ 0.12 of A100 bf16 peak (312 TFLOP/s)
counting 6·N·D + attention FLOPs with the reference's recompute settings.
A single v5e chip cannot hold 7B training state, so the bench trains a
Llama-architecture model sized to the chip and reports **MFU**, which is the
hardware-normalized apples-to-apples number; vs_baseline = our MFU / 0.12.

Besides the headline (seq 1024, the reference's finetune config), the JSON
carries a seq-length MFU curve through 32k (BASELINE config 4's long-context
regime, exercising the Pallas flash kernel fwd+bwd) and a KV-cache decode
throughput row.  Sweep provenance (v5e, 2026-07): head_dim 128 beats 64 by
+24% MFU (MXU lane width); mb=12 beats 8/16 by ~1%; the fused LM head and
block_q/k ∈ {512, 2048} variants measured slower — defaults kept.
Decode negative results (v5e, 2026-07-31, don't re-chase): per-step decode
time is flat in cache max_len (no hidden O(max_len) copies) and scales with
LAYER COUNT at fixed weight bytes (6-layer/h2048 is 25% faster per step
than 24-layer/h1024 with MORE bytes) — the bound is the sequential per-op
chain, ~100us/layer vs a 38us/layer weight-read floor.  Fusing sibling
GEMVs (wqkv, gate|up concat) measured 1.01x: XLA's scheduler already
overlaps independent siblings, and the wider bf16 matmul perturbs logits
(different accumulation tiling, max|dlogit| 0.057).  Closing the gap needs
shorter sequential chains (per-layer Pallas megakernels or speculative
multi-token steps), not op-count reduction.
"""

from __future__ import annotations

import json
import time

import numpy as np


def _model_flops_per_token(cfg, seq_len: int) -> float:
    """6·N·D-style training FLOPs/token (fwd+bwd = 3× fwd) + attention."""
    h = cfg.hidden_size
    d = cfg.head_dim
    nq = cfg.num_attention_heads
    nkv = cfg.kv_heads
    ffn = cfg.ffn_size
    n_mlp = 3 if cfg.is_glu else 2
    per_layer_fwd = (
        2 * h * (nq * d) + 2 * 2 * h * (nkv * d) + 2 * (nq * d) * h
        + n_mlp * 2 * h * ffn
        + 2 * 2 * nq * d * seq_len  # scores + context, causal-halved ×2
    )
    fwd = cfg.num_layers * per_layer_fwd + 2 * h * cfg.padded_vocab_size()
    return 3.0 * fwd  # fwd + bwd


def chip_peak_flops(device_kind: str) -> float:
    """bf16 peak FLOP/s per chip for MFU normalization (also used by the
    tests_tpu MFU regression guard)."""
    peaks = {
        "v5 lite": 197e12, "v5e": 197e12,
        "v5p": 459e12, "v5": 459e12,
        "v4": 275e12, "v6e": 918e12, "v6 lite": 918e12,
    }
    kind = device_kind.lower().replace("tpu ", "")
    return next((v for k, v in peaks.items() if k in kind), 197e12)


def chip_hbm_bandwidth(device_kind: str) -> float:
    """HBM bytes/s per chip, for the decode bandwidth roofline."""
    bws = {
        "v5 lite": 819e9, "v5e": 819e9,
        "v5p": 2765e9, "v5": 2765e9,
        "v4": 1228e9, "v6e": 1640e9, "v6 lite": 1640e9,
    }
    kind = device_kind.lower().replace("tpu ", "")
    return next((v for k, v in bws.items() if k in kind), 819e9)


def _bench_model(seq: int, recompute: str):
    from megatron_llm_tpu.config import llama2_config

    # Llama-architecture model sized to one chip.  8 heads × d=128 (not
    # 16 × 64): the 128-wide head dim matches the MXU lane width and
    # measures ~24% faster at identical params/FLOPs.
    return llama2_config(
        "7b",
        hidden_size=1024,
        num_layers=24,
        num_attention_heads=8,
        num_kv_heads=8,
        ffn_hidden_size=2816,
        seq_length=seq,
        max_position_embeddings=seq,
        params_dtype="bfloat16",
        attention_impl="flash",
        recompute=recompute,
    )


def _bench_model_7b_width(seq: int, num_layers: int,
                          recompute: str = "selective"):
    """Llama-2-7B *width* (hidden 4096, ffn 11008, 32 q-heads × d128) at
    reduced depth so training state fits one chip; GQA (8 kv-heads) trims
    the kv projections the way the 34B/70B presets do.  MFU at this width
    is the number comparable to the BASELINE 7B configs — per-layer matmul
    shapes are exactly the 7B ones, depth only repeats them."""
    from megatron_llm_tpu.config import llama2_config

    return llama2_config(
        "7b",
        hidden_size=4096,
        num_layers=num_layers,
        num_attention_heads=32,
        num_kv_heads=8,
        ffn_hidden_size=11008,
        seq_length=seq,
        max_position_embeddings=seq,
        params_dtype="bfloat16",
        attention_impl="flash",
        recompute=recompute,
    )


def _train_point(seq: int, mb: int, recompute: str, iters: int, peak: float,
                 model=None):
    """One training-throughput measurement → (tokens/sec, mfu, loss)."""
    import jax
    import jax.numpy as jnp

    from megatron_llm_tpu.config import (
        OptimizerConfig,
        ParallelConfig,
        RuntimeConfig,
        TrainConfig,
    )
    from megatron_llm_tpu.models import model as model_lib
    from megatron_llm_tpu.training.step import init_train_state, make_train_step

    cfg = RuntimeConfig(
        model=model if model is not None else _bench_model(seq, recompute),
        parallel=ParallelConfig(),
        optimizer=OptimizerConfig(lr=1e-4, clip_grad=1.0),
        train=TrainConfig(train_iters=100, micro_batch_size=mb,
                          global_batch_size=mb, seq_length=seq),
    ).validate()

    params = model_lib.init_params(jax.random.key(0), cfg.model)
    n_params = sum(p.size for p in jax.tree.leaves(params))
    state = init_train_state(cfg, params)
    step = make_train_step(cfg)

    rng = np.random.default_rng(0)
    shape = (1, mb, seq)  # one microbatch per step
    tokens = rng.integers(0, cfg.model.vocab_size, shape)
    batch = {
        "tokens": jnp.asarray(tokens, jnp.int32),
        "labels": jnp.asarray(np.roll(tokens, -1, -1), jnp.int32),
        "loss_mask": jnp.ones(shape, jnp.float32),
    }
    key = jax.random.key(0)

    # warmup / compile — two steps: the first compiles, the second flushes
    # remaining lazy one-time work (allocator growth, executable warm-in)
    # out of the timed window (~0.8% of a 20-iter headline otherwise)
    state, metrics = step(state, batch, key)
    float(metrics["loss"])
    state, metrics = step(state, batch, key)
    float(metrics["loss"])

    # Timing via an explicit host fetch of the last loss: the steps chain
    # through the donated state, so the fetch transitively waits for all of
    # them.  (block_until_ready proved unreliable for independent outputs
    # over the axon-tunneled backend; a host read is unambiguous.)
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, batch, key)
    loss = float(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens_per_sec = iters * mb * seq / dt
    mfu = tokens_per_sec * _model_flops_per_token(cfg.model, seq) / peak
    # Drop this point's state/executables before the next point compiles:
    # carried-over HBM allocations made the 32k row intermittently spill
    # (measured 0.63 isolated vs 0.17 contaminated in one process).
    del state, batch, step
    if seq >= 8192 or model is not None:  # big points: free HBM + caches
        jax.clear_caches()
    return tokens_per_sec, mfu, loss, n_params


def _decode_roofline_tps(cfg, param_bytes: int, batch: int,
                         avg_cache_len: int, hbm_bw: float) -> float:
    """Bandwidth-bound decode tokens/s: each decode step must stream the
    weights once (shared across the batch; ``param_bytes`` = actual stored
    bytes, so int8 quantization moves the roofline) plus each sequence's
    bf16 KV cache; tokens/s = batch / (bytes_per_step / HBM_BW).  Compute
    and the int32 token traffic are negligible beside these two terms, so
    the bound is tight for small batches (the reference publishes no
    decode number; this roofline is the stated target per BASELINE.md)."""
    kv_elt_bytes = (1 + 4 / cfg.head_dim
                    if cfg.kv_cache_quant == "int8" else 2)
    kv_bytes = int(batch * 2 * cfg.num_layers * cfg.kv_heads
                   * cfg.head_dim * avg_cache_len * kv_elt_bytes)
    return batch / ((param_bytes + kv_bytes) / hbm_bw)


def _decode_point(hbm_bw: float, quantize: bool = False):
    """→ (decode tokens/sec, roofline tokens/sec, prefill tokens/sec) on
    the bench model.  With ``quantize`` both the weights (ops/quant.py)
    AND the KV cache (ops/kv_quant.py) are int8, and both roofline terms
    shrink accordingly."""
    import jax
    import jax.numpy as jnp

    from megatron_llm_tpu.models import model as model_lib
    from megatron_llm_tpu.generation.generation import generate_tokens

    # gen_len 512 (not 128): the decode rate comes from subtracting a
    # separately-timed prefill from the full-generate window, and with a
    # short horizon the two terms are comparable — tunnel timing jitter
    # on the prefill term then swings the decode estimate by ±40%
    # (observed 2.6k-4.9k tok/s across clean runs at gen 128).  At 512
    # steps the prefill correction is a few percent of the window, so its
    # jitter moves the decode number by ~1%.
    b, prompt_len, gen_len = 8, 128, 512
    # The kv-cache path has its own dispatcher (ops/attention.py:
    # decode_attention): Pallas decode kernel on TPU, einsum fallback —
    # cfg.attention_impl only affects the prefill, where flash is right.
    cfg = _bench_model(prompt_len + gen_len, "selective")
    if quantize:
        import dataclasses

        cfg = dataclasses.replace(cfg, kv_cache_quant="int8").validate()
    params = model_lib.init_params(jax.random.key(0), cfg)
    if quantize:
        from megatron_llm_tpu.ops.quant import quantize_params

        params = quantize_params(params)

    rng = np.random.default_rng(1)
    tokens = np.zeros((b, prompt_len + gen_len), np.int32)
    tokens[:, :prompt_len] = rng.integers(1, cfg.vocab_size,
                                          (b, prompt_len))
    tokens = jnp.asarray(tokens)
    lengths = jnp.full((b,), prompt_len, jnp.int32)

    def _min_time(run, n=3):
        """Best-of-n wall time: tunnel latency drifts wildly between runs
        (the same decode program measured 3.3k-4.9k tok/s across clean
        full-bench runs), and the dt_full - dt_prefill subtraction below
        AMPLIFIES single-shot jitter (a high prefill sample inflates
        decode tps and vice versa) — minimums of repeated samples keep
        the official record off the noise tails for ~20s of wall-clock."""
        best = float("inf")
        for _ in range(n):
            t0 = time.perf_counter()
            jax.device_get(run())
            best = min(best, time.perf_counter() - t0)
        return best

    out = generate_tokens(cfg, params, tokens, lengths,
                          use_eos_stop=False)  # warmup/compile
    jax.device_get(out.tokens)
    dt_full = _min_time(lambda: generate_tokens(
        cfg, params, tokens, lengths, use_eos_stop=False).tokens)

    # The roofline models per-step decode streaming only, so subtract the
    # prefill forward (the same [b, prompt_len] cached forward the generate
    # loop runs before its first decode step) from the measured window —
    # otherwise the reported fraction is systematically understated by the
    # prefill's share of dt.
    rope = model_lib.rope_tables(cfg)

    @jax.jit
    def prefill(p, toks):
        k, v = model_lib.init_kv_cache(cfg, b, prompt_len + gen_len)
        logits, k, v = model_lib.forward_cached(
            cfg, p, toks, k, v, jnp.int32(0), rope=rope)
        return logits[:, -1]

    jax.device_get(prefill(params, tokens[:, :prompt_len]))  # compile
    dt_prefill = _min_time(lambda: prefill(params, tokens[:, :prompt_len]))

    dt = max(dt_full - dt_prefill, 1e-9)
    tps = b * gen_len / dt
    prefill_tps = b * prompt_len / max(dt_prefill, 1e-9)
    param_bytes = sum(p.size * p.dtype.itemsize
                      for p in jax.tree.leaves(params))
    roof = _decode_roofline_tps(cfg, param_bytes, b,
                                prompt_len + gen_len // 2, hbm_bw)
    return tps, roof, prefill_tps


def _transient_error_types():
    """The error classes worth retrying: the axon-tunneled compile service
    occasionally throws a transient remote-compile XlaRuntimeError.
    Deterministic bugs (NameError, TypeError, ...) must NOT be retried —
    round 2's broad ``except Exception`` retried a NameError once and then
    sank the whole benchmark, doubling the cost of diagnosing it."""
    import jax

    types = [jax.errors.JaxRuntimeError]
    try:
        from jax._src.lib import _jax

        types.append(_jax.XlaRuntimeError)
    except Exception:  # noqa: BLE001 — internal layout varies by version
        pass
    return tuple(types)


def _retry(fn, *args):
    """One retry, transient (XLA runtime / remote-compile) errors only."""
    try:
        return fn(*args)
    except _transient_error_types() as e:
        print(f"# bench point failed ({type(e).__name__}); retrying once",
              flush=True)
        import jax

        jax.clear_caches()
        time.sleep(5)
        return fn(*args)


def _point(label: str, fn, *args):
    """Run one measurement, isolated: a failed point (even a deterministic
    crash) yields None and the benchmark still emits its JSON — round 2
    lost the already-measured train curve because a later decode point
    crashed before the single end-of-run print."""
    t0 = time.perf_counter()
    try:
        out = _retry(fn, *args)
    except Exception as e:  # noqa: BLE001 — isolation barrier, reported
        print(f"# bench point {label} FAILED: {type(e).__name__}: {e}",
              flush=True)
        return None
    print(f"# bench point {label} ok ({time.perf_counter() - t0:.0f}s)",
          flush=True)
    return out


def _detect_device(timeout_s: int = 240):
    """First device's kind, probed in a SUBPROCESS with a hard timeout.

    A degraded axon tunnel makes ``jax.devices()`` hang indefinitely
    *inside a C call* (observed live: >25 min wedged, and SIGALRM never
    fires because the Python handler can't run mid-C-call) — a benchmark
    that hangs is worse for the driver than one that emits a structured
    failure record quickly.  A killed subprocess bounds the wait no
    matter where the backend blocks; on success the parent initializes
    its own backend (now known reachable)."""
    import subprocess
    import sys

    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].device_kind)"],
            capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        raise TimeoutError(
            f"device probe exceeded {timeout_s}s "
            "(accelerator tunnel unreachable?)")
    if out.returncode != 0:
        tail = (out.stderr or "").strip().splitlines()[-1:] or ["?"]
        raise RuntimeError(f"device probe failed: {tail[0]}")
    # the child already printed the device kind; re-calling jax.devices()
    # here would reintroduce the unbounded hang (a wedge can start between
    # the probe and the call) and pay backend init twice
    kind = (out.stdout or "").strip().splitlines()[-1:]
    if not kind:
        raise RuntimeError("device probe printed nothing")
    return kind[0]


def main() -> None:
    try:
        platform = _detect_device()
    except (TimeoutError, RuntimeError, OSError) as e:
        # no reachable device: emit a parseable record naming the cause
        # instead of hanging or stack-tracing
        print(json.dumps({
            "metric": "mfu", "value": None, "unit": "fraction_of_peak",
            "vs_baseline": None,
            "error": f"{type(e).__name__}: {e}",
        }))
        raise SystemExit(1)
    peak = chip_peak_flops(platform)

    # Headline: seq 1024 (the reference's finetune config), measured
    # single-chip sweet spot mb=12, selective recompute.  Fallback config
    # (mb=8) only runs if the primary fails — a partial record with a real
    # headline beats a stack trace.
    headline = _point("train@1024", _train_point, 1024, 12, "selective",
                      30, peak)
    headline_config = "mb12"
    if headline is None:
        headline = _point("train@1024/fallback", _train_point, 1024, 8,
                          "selective", 10, peak)
        headline_config = "mb8-fallback"

    curve = []
    if headline is not None:
        tps, mfu, loss, n_params = headline
        curve.append({"seq_length": 1024, "mfu": round(mfu, 4),
                      "tokens_per_sec": round(tps, 1)})

    # MFU-vs-seq curve (BASELINE config 4 regime at 32k): selective remat
    # while it fits, full remat beyond 8k.
    for seq, mb, rc, iters in ((4096, 3, "selective", 10),
                               (8192, 1, "selective", 10),
                               (16384, 1, "full", 5),
                               (32768, 1, "full", 5)):
        p = _point(f"train@{seq}", _train_point, seq, mb, rc, iters, peak)
        if p is not None:
            c_tps, c_mfu, _, _ = p
            curve.append({"seq_length": seq, "mfu": round(c_mfu, 4),
                          "tokens_per_sec": round(c_tps, 1)})

    # 7B-width point (BASELINE configs are all 7B–70B; the 374M proxy's
    # matmuls are narrower than any of them).  Shallow depth to fit
    # ~11-13 GB of train state in one chip's HBM.  Measured ladder on
    # v5e (2026-07-31): L3/mb2/selective 0.556, L2/mb2/selective 0.535,
    # L3/mb1/full 0.441 — mb ≥ 2 + selective remat is the lever; the
    # full-remat L2 rung is the spill fallback.
    wide = None
    for layers, mb, rc in ((3, 2, "selective"), (2, 2, "selective"),
                           (2, 1, "full")):
        wide = _point(f"train@4096/7b-width-L{layers}", _train_point,
                      4096, mb, rc, 5, peak,
                      _bench_model_7b_width(4096, layers, rc))
        if wide is not None:
            w_tps, w_mfu, _, w_params = wide
            curve.append({"seq_length": 4096, "mfu": round(w_mfu, 4),
                          "tokens_per_sec": round(w_tps, 1),
                          "config": f"7b-width-L{layers}-mb{mb}-{rc}",
                          "model_params": w_params})
            break

    hbm_bw = chip_hbm_bandwidth(platform)
    decode = _point("decode", _decode_point, hbm_bw)
    decode_q = _point("decode/int8", _decode_point, hbm_bw, True)

    baseline_mfu = 0.12  # reference 890 tok/s/GPU on A100 ⇒ ~0.12 MFU
    record = {
        "metric": "mfu",
        "value": None,
        "unit": "fraction_of_peak",
        "vs_baseline": None,
        "seq_length": 1024,
        "device": platform,
        "mfu_vs_seq": curve,
        "decode_tokens_per_sec": (None if decode is None
                                  else round(decode[0], 1)),
        "decode_roofline_tokens_per_sec": (None if decode is None
                                           else round(decode[1], 1)),
        "decode_roofline_frac": (None if decode is None
                                 else round(decode[0] / decode[1], 4)),
        "decode_tokens_per_sec_int8": (None if decode_q is None
                                       else round(decode_q[0], 1)),
        "decode_int8_roofline_frac": (None if decode_q is None
                                      else round(decode_q[0] / decode_q[1],
                                                 4)),
        "prefill_tokens_per_sec": (None if decode is None
                                   else round(decode[2], 1)),
    }
    if headline is not None:
        record.update({
            "value": round(mfu, 4),
            "vs_baseline": round(mfu / baseline_mfu, 3),
            "tokens_per_sec_per_chip": round(tps, 1),
            "model_params": n_params,
            "loss": loss,
            "headline_config": headline_config,
        })
    print(json.dumps(record))


if __name__ == "__main__":
    main()
