#!/usr/bin/env python3
"""Shim: run the tpulint static pass from anywhere in the repo.

Equivalent to ``python -m megatron_llm_tpu.analysis``; exists so CI and
editors can invoke a plain script path.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from megatron_llm_tpu.analysis.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
