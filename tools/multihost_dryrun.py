"""Two-process multihost dryrun on localhost CPU devices.

Exercises every multi-*process* code path that single-process tests cannot:
``initialize.initialize_distributed`` rendezvous, a global mesh spanning
processes (dp axis across hosts), per-process data feeding
(``jax.make_array_from_callback`` over the global batch sharding), the
``_cluster_any`` signal consensus (driver.DistSignalHandler's agreement
primitive), rank-0 printing, and a coordinated orbax save + load.

Reference parity: megatron/initialize.py:124-151 (init_process_group),
dist_signal_handler.py:50-81 (all-gather receipt), checkpointing.py:243-333
(rank-coordinated save).

Run directly (spawns its own two workers):
    python tools/multihost_dryrun.py
Each worker gets 4 local CPU devices → an 8-device global mesh (dp=2, tp=4).
Also wrapped as a test in tests/training/test_multihost.py.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile


def worker(process_id: int, num_processes: int, coordinator: str,
           ckpt_dir: str) -> None:
    import jax

    from megatron_llm_tpu.initialize import initialize_distributed

    initialize_distributed(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    assert jax.process_count() == num_processes, jax.process_count()
    assert jax.process_index() == process_id

    import jax.numpy as jnp
    import numpy as np

    from megatron_llm_tpu.config import (
        OptimizerConfig,
        ParallelConfig,
        RuntimeConfig,
        TrainConfig,
        tiny_config,
    )
    from megatron_llm_tpu import checkpointing
    from megatron_llm_tpu.parallel import mesh as mesh_lib
    from megatron_llm_tpu.training import driver as driver_lib

    n_global = len(jax.devices())
    assert n_global == 8, f"expected 8 global devices, got {n_global}"

    # dp=2 spans the two processes (each holds 4 local devices → tp=4 local).
    parallel = ParallelConfig(data_parallel=2, tensor_parallel=4,
                              use_distributed_optimizer=True)
    cfg = RuntimeConfig(
        model=tiny_config(
            hidden_size=64, num_layers=2, num_attention_heads=8,
            num_kv_heads=8, ffn_hidden_size=128, vocab_size=256,
            seq_length=32, make_vocab_size_divisible_by=32),
        parallel=parallel,
        optimizer=OptimizerConfig(lr=1e-3, clip_grad=1.0),
        train=TrainConfig(train_iters=2, micro_batch_size=2,
                          global_batch_size=4, seq_length=32),
    ).validate()

    art = driver_lib.setup_train_state(cfg)
    driver_lib.print_rank_0("multihost: state sharded over",
                            dict(art.mesh.shape))

    # Per-process data feeding: every process computes the same global numpy
    # batch deterministically and contributes only its addressable shards.
    rng = np.random.default_rng(0)
    shape = (1, 4, 32)  # [accum, batch(dp-sharded), seq]
    toks = rng.integers(0, 256, shape)
    np_batch = {
        "tokens": toks.astype(np.int32),
        "labels": np.roll(toks, -1, -1).astype(np.int32),
        "loss_mask": np.ones(shape, np.float32),
    }
    batch = {
        k: jax.make_array_from_callback(
            v.shape, art.batch_sharding, lambda idx, v=v: v[idx])
        for k, v in np_batch.items()
    }

    state = art.state
    losses = []
    for _ in range(2):
        state, metrics = art.step_fn(state, batch, None)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(l) for l in losses), losses

    # Signal consensus: only process 1 "receives" the signal; every process
    # must still agree True (and all-False must agree False).
    assert driver_lib._cluster_any(process_id == 1) is True
    assert driver_lib._cluster_any(False) is False

    # Coordinated orbax save from all processes, then a fresh load against
    # the sharded template (resharding-on-load path included).
    checkpointing.save_checkpoint(ckpt_dir, state, cfg=cfg,
                                  meta={"consumed_samples": 8})
    restored, it = checkpointing.load_checkpoint(ckpt_dir, state)
    assert int(it) == 2, it
    np.testing.assert_allclose(
        np.asarray(jax.device_get(restored.opt.step)),
        np.asarray(jax.device_get(state.opt.step)))
    meta = checkpointing.load_meta(ckpt_dir)
    assert meta.get("consumed_samples") == 8, meta

    driver_lib.print_rank_0(json.dumps({
        "multihost": "ok",
        "processes": num_processes,
        "mesh": dict(art.mesh.shape),
        "losses": [round(l, 4) for l in losses],
    }))


def launch(num_processes: int = 2, port: int = 12657) -> int:
    """Spawn the workers and wait; returns the first nonzero exit code."""
    env_base = dict(os.environ)
    env_base.pop("JAX_PLATFORMS", None)
    with tempfile.TemporaryDirectory() as ckpt_dir:
        procs = []
        for pid in range(num_processes):
            env = dict(
                env_base,
                JAX_PLATFORMS="cpu",
                XLA_FLAGS="--xla_force_host_platform_device_count=4",
                PALLAS_AXON_POOL_IPS="",  # disarm any TPU sitecustomize
                MEGATRON_TPU_MULTIHOST_WORKER=str(pid),
                MEGATRON_TPU_MULTIHOST_COORD=f"localhost:{port}",
                MEGATRON_TPU_MULTIHOST_N=str(num_processes),
                MEGATRON_TPU_MULTIHOST_CKPT=ckpt_dir,
            )
            procs.append(subprocess.Popen(
                [sys.executable, os.path.abspath(__file__)], env=env))
        rcs = [p.wait(timeout=600) for p in procs]
    return next((rc for rc in rcs if rc), 0)


if __name__ == "__main__":
    wid = os.environ.get("MEGATRON_TPU_MULTIHOST_WORKER")
    if wid is None:
        sys.exit(launch())
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    worker(int(wid),
           int(os.environ["MEGATRON_TPU_MULTIHOST_N"]),
           os.environ["MEGATRON_TPU_MULTIHOST_COORD"],
           os.environ["MEGATRON_TPU_MULTIHOST_CKPT"])
