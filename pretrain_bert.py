"""BERT pretraining entry point (reference: pretrain_bert.py).

The corpus is a sentence-per-item .bin/.idx indexed dataset (preprocess
with ``--split_sentences``-style input: one sentence per ``add_item``,
documents separated by ``end_document``).

Example:
  python pretrain_bert.py --data_path corpus --tokenizer_model \
      bert-base-uncased --seq_length 128 --train_iters 1000 --save ckpts/
"""

from __future__ import annotations

import argparse

import jax

from megatron_llm_tpu.config import (
    ModelConfig, OptimizerConfig, ParallelConfig, RuntimeConfig, TrainConfig,
)
from megatron_llm_tpu.data.bert_dataset import BertDataset, BertSpecialTokens
from megatron_llm_tpu.data.indexed_dataset import MMapIndexedDataset
from megatron_llm_tpu.models import encdec
from megatron_llm_tpu.training.driver import pretrain_custom


def get_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data_path", required=True)
    p.add_argument("--tokenizer_model", default="bert-base-uncased")
    p.add_argument("--vocab_size", type=int, default=None,
                   help="override (skips loading the tokenizer)")
    p.add_argument("--hidden_size", type=int, default=768)
    p.add_argument("--num_layers", type=int, default=12)
    p.add_argument("--num_attention_heads", type=int, default=12)
    p.add_argument("--seq_length", type=int, default=512)
    p.add_argument("--micro_batch_size", type=int, default=4)
    p.add_argument("--global_batch_size", type=int, default=32)
    p.add_argument("--train_iters", type=int, default=1000)
    p.add_argument("--lr", type=float, default=1e-4)
    p.add_argument("--save", default=None)
    p.add_argument("--save_interval", type=int, default=500)
    p.add_argument("--log_interval", type=int, default=10)
    p.add_argument("--data_parallel", type=int, default=1)
    p.add_argument("--tensor_parallel", type=int, default=1)
    p.add_argument("--pipeline_parallel", type=int, default=1,
                   help="encoder pipeline over pp stages (reference "
                        "trains BERT through the same 1F1B schedule)")
    p.add_argument("--use_distributed_optimizer", action="store_true",
                   help="ZeRO-1: shard optimizer state over dp")
    p.add_argument("--seed", type=int, default=1234)
    p.add_argument("--masked_lm_prob", type=float, default=0.15)
    return p.parse_args(argv)


def bert_runtime_config(args, vocab_size: int) -> RuntimeConfig:
    model = ModelConfig(
        vocab_size=vocab_size,
        hidden_size=args.hidden_size,
        num_layers=args.num_layers,
        num_attention_heads=args.num_attention_heads,
        num_kv_heads=args.num_attention_heads,
        ffn_hidden_size=4 * args.hidden_size,
        max_position_embeddings=args.seq_length,
        norm_type="layernorm",
        activation="gelu",
        position_embedding_type="absolute",
        use_bias=True,
        tie_embed_logits=True,
        tokentype_size=2,
        hidden_dropout=0.1,
        attention_dropout=0.1,
        seq_length=args.seq_length,
    )
    accum = args.global_batch_size // (args.micro_batch_size
                                       * args.data_parallel)
    return RuntimeConfig(
        model=model,
        parallel=ParallelConfig(data_parallel=args.data_parallel,
                                tensor_parallel=args.tensor_parallel,
                                pipeline_parallel=args.pipeline_parallel,
                                num_microbatches=accum,
                                use_distributed_optimizer=
                                args.use_distributed_optimizer),
        optimizer=OptimizerConfig(lr=args.lr, clip_grad=1.0),
        train=TrainConfig(
            train_iters=args.train_iters,
            micro_batch_size=args.micro_batch_size,
            global_batch_size=args.global_batch_size,
            seq_length=args.seq_length,
            save=args.save, save_interval=args.save_interval,
            log_interval=args.log_interval, seed=args.seed,
        ),
    ).validate()


def bert_loss_fn(cfg, params, mb, rng, deterministic):
    return encdec.bert_loss(cfg.model, params, mb, rng, deterministic)


def main(argv=None):
    args = get_args(argv)
    if args.vocab_size is not None:
        vocab = args.vocab_size
        special = BertSpecialTokens(cls=vocab - 4, sep=vocab - 3,
                                    mask=vocab - 2, pad=0)
    else:
        from megatron_llm_tpu.tokenizer.tokenizer import build_tokenizer

        tok = build_tokenizer("huggingface", args.tokenizer_model)
        inner = tok.inner
        vocab = tok.vocab_size
        special = BertSpecialTokens(
            cls=inner.cls_token_id, sep=inner.sep_token_id,
            mask=inner.mask_token_id, pad=inner.pad_token_id or 0)

    cfg = bert_runtime_config(args, vocab)
    ds = BertDataset(
        MMapIndexedDataset(args.data_path), cfg.train.seq_length,
        cfg.model.vocab_size, special,
        masked_lm_prob=args.masked_lm_prob, seed=args.seed)
    params = encdec.init_bert_params(jax.random.key(args.seed), cfg.model,
                                     tp=args.tensor_parallel)
    specs = (encdec.bert_param_specs(cfg.model, cfg.parallel)
             if (args.tensor_parallel > 1
                 or args.use_distributed_optimizer) else None)
    pipeline_loss_fn = None
    if args.pipeline_parallel > 1:
        from megatron_llm_tpu.parallel import pipeline_encdec as pe

        params = pe.bert_to_pipeline_params(params, cfg.parallel)
        specs = pe.bert_pipeline_param_specs(cfg.model, cfg.parallel)
        pipeline_loss_fn = pe.bert_pipeline_loss
    return pretrain_custom(cfg, ds, params, bert_loss_fn, param_specs=specs,
                           pipeline_loss_fn=pipeline_loss_fn)


if __name__ == "__main__":
    main()
